"""INT8 quantization (reference ``python/mxnet/contrib/quantization.py``
driving `src/operator/quantization/` N24: post-training quantization with
minmax/entropy calibration).

TPU-native design: weight quantization packs int8 per-channel (jnp int8
arrays — XLA lowers int8 matmul/conv efficiently on newer TPUs), activation
quantization is simulated (quantize→dequantize at op boundaries) with
scales from calibration, which is what the reference's `calib_mode='naive'`
(minmax) and `'entropy'` (KL) produce. API parity: ``quantize_model`` for
the Symbol path, ``quantize_net`` for Gluon.
"""
from __future__ import annotations

import logging

import numpy as np
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray

__all__ = ["quantize_model", "quantize_net", "quantize_params",
           "CalibrationCollector"]


def _minmax_scale(arr):
    m = float(np.abs(arr).max()) if arr.size else 1.0
    return m / 127.0 if m > 0 else 1.0


def _entropy_scale(arr, num_bins=2048, num_quantized_bins=255):
    """KL-divergence threshold search (reference quantization.py
    _get_optimal_threshold / `quantize_graph_pass.cc` calibration)."""
    arr = np.abs(np.asarray(arr).ravel())
    mx_val = arr.max() if arr.size else 1.0
    if mx_val == 0:
        return 1.0
    hist, edges = np.histogram(arr, bins=num_bins, range=(0, mx_val))
    best_kl = np.inf
    best_t = mx_val
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, num_bins // 64)):
        t = edges[i] if i < len(edges) else mx_val
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()  # clip outliers into last bin
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = i / num_quantized_bins
        q = np.zeros(i)
        for j in range(num_quantized_bins):
            lo = int(j * factor)
            hi = max(int((j + 1) * factor), lo + 1)
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        p_n = p / p.sum()
        q_n = q / q.sum() if q.sum() else q
        mask = p_n > 0
        kl = float(np.sum(p_n[mask] * np.log(
            p_n[mask] / np.maximum(q_n[mask], 1e-12))))
        if kl < best_kl:
            best_kl = kl
            best_t = t
    return best_t / 127.0


def quantize_params(params, per_channel=True):
    """float params → (int8 values, scales) dicts."""
    qparams = {}
    scales = {}
    for name, p in params.items():
        arr = p.asnumpy() if hasattr(p, "asnumpy") else np.asarray(p)
        if arr.ndim >= 2 and per_channel:
            ax = tuple(range(1, arr.ndim))
            s = np.maximum(np.abs(arr).max(axis=ax), 1e-12) / 127.0
            q = np.clip(np.round(arr / s.reshape((-1,) + (1,) *
                                                 (arr.ndim - 1))),
                        -127, 127).astype(np.int8)
        else:
            s = np.float32(_minmax_scale(arr))
            q = np.clip(np.round(arr / s), -127, 127).astype(np.int8)
        qparams[name] = q
        scales[name] = s
    return qparams, scales


class CalibrationCollector:
    """Collect per-layer output ranges during calibration forwards
    (reference quantization.py _LayerOutputCollector)."""

    def __init__(self, mode="naive"):
        assert mode in ("naive", "entropy")
        self.mode = mode
        self._samples = {}

    def collect(self, name, arr):
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        self._samples.setdefault(name, []).append(a.ravel())

    def scales(self):
        out = {}
        for name, chunks in self._samples.items():
            arr = np.concatenate(chunks)
            out[name] = (_minmax_scale(arr) if self.mode == "naive"
                         else _entropy_scale(arr))
        return out


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=logging, **kwargs):
    """Symbol-path post-training quantization (reference
    quantization.py:430 quantize_model). Weights are int8-quantized;
    returns (sym, qarg_params, aux_params) where quantized weights are
    stored dequantized-on-load (simulated quantization, same accuracy
    semantics as the reference's int8 graph on non-VNNI CPUs)."""
    excluded = set(excluded_sym_names or [])
    qargs = {}
    for name, p in arg_params.items():
        if name in excluded or not name.endswith("weight"):
            qargs[name] = p
            continue
        q, s = quantize_params({name: p})
        qv = q[name].astype(np.float32)
        sv = s[name]
        deq = qv * (sv.reshape((-1,) + (1,) * (qv.ndim - 1))
                    if np.ndim(sv) else sv)
        from ..ndarray import ndarray as _nd
        qargs[name] = _nd.array(deq.astype("float32"))
    logger.info("quantized %d weight tensors to int8", len(qargs))
    return sym, qargs, aux_params


def _int8_identity_base():
    from ..gluon.block import Block
    return Block


def _int8_blocks():
    """Lazily-built int8 inference Blocks over the quantized op family
    (reference's int8 graph rewrite, `quantize_graph_pass.cc`, done here as
    a Gluon block swap). The int8 x int8 -> int32 matmul/conv rides the MXU
    int8 path on TPU; ranges travel as (1,) tensors exactly like the
    reference's min/max outputs."""
    from ..gluon.block import Block
    from ..ops.registry import get_op

    _quant = get_op("_contrib_quantize_v2")
    _fc = get_op("_contrib_quantized_fully_connected")
    _conv = get_op("_contrib_quantized_conv")
    _deq = get_op("_contrib_dequantize")

    from ..gluon.parameter import Constant
    from ..ndarray import ndarray as _ndm

    def _const_param(name, arr, dtype):
        p = Constant(name, _ndm.array(arr, dtype=dtype))
        p.initialize()
        return p

    class _Int8Layer(Block):
        """int8 weights, weight range, bias and the calibrated activation
        range are REGISTERED Parameters, so ``save_parameters`` /
        ``load_parameters`` round-trip a quantized net (round-2 advisor
        finding: plain attributes were silently dropped). ``calib`` holds
        (min, max); NaN means uncalibrated → dynamic per-batch ranges."""

        def __init__(self, weight, bias, act):
            super().__init__()
            w = weight.astype(np.float32)
            amax = max(float(np.abs(w).max()), 1e-12)
            q = np.clip(np.round(w / (amax / 127.0)), -127,
                        127).astype(np.int8)
            self.qweight = _const_param("qweight", q, "int8")
            self.wrange = _const_param(
                "wrange", np.array([-amax, amax], np.float32), "float32")
            self.qbias = None if bias is None else _const_param(
                "qbias", bias.astype(np.float32), "float32")
            self.calib = _const_param(
                "calib", np.array([np.nan, np.nan], np.float32), "float32")
            self._act = act
            self._calibrating = False
            self._range = None      # runtime cache of the calib Parameter
            self._range_src = None  # jax buffer the cache was read from

        @property
        def _wq(self):
            return self.qweight.data()

        @property
        def _wmn(self):
            return self.wrange.data()[0:1]

        @property
        def _wmx(self):
            return self.wrange.data()[1:2]

        @property
        def _b(self):
            return None if self.qbias is None else self.qbias.data()

        def _freeze_calibration(self):
            if self._range is not None:
                self.calib.set_data(_ndm.array(
                    np.asarray(self._range, np.float32)))
                self._range_src = self.calib.data()._data

        def _calib_range(self):
            # host read only when the underlying buffer changed (jax
            # arrays are immutable, so identity identifies the value) —
            # load_parameters() after a forward is still picked up, and
            # steady-state forwards pay no device sync
            cur = self.calib.data()._data
            if cur is not self._range_src:
                rng = np.asarray(cur)
                self._range = (None if np.isnan(rng[0])
                               else [float(rng[0]), float(rng[1])])
                self._range_src = cur
            return self._range

        def _quantize_in(self, x):
            if self._calibrating:
                xn = x.asnumpy()
                lo, hi = float(xn.min()), float(xn.max())
                if self._range is None:
                    self._range = [lo, hi]
                else:
                    self._range = [min(self._range[0], lo),
                                   max(self._range[1], hi)]
                return _quant(x)
            rng = self._calib_range()
            if rng is not None:
                return _quant(x, min_calib_range=rng[0],
                              max_calib_range=rng[1])
            return _quant(x)

    class _Int8Dense(_Int8Layer):
        def __init__(self, dense):
            super().__init__(dense.weight.data().asnumpy(),
                             None if dense.bias is None
                             else dense.bias.data().asnumpy(), dense.act)
            self._units = dense._units
            self._flatten = dense._flatten

        def forward(self, x):
            qx, xmn, xmx = self._quantize_in(x)
            acc, omn, omx = _fc(qx, self._wq, None, xmn, xmx, self._wmn,
                                self._wmx, no_bias=True,
                                num_hidden=self._units,
                                flatten=self._flatten)
            y = _deq(acc, omn, omx)
            if self._b is not None:
                y = y + self._b
            return y if self._act is None else self._act(y)

    class _Int8Conv(_Int8Layer):
        def __init__(self, conv, weight_override=None, bias_override=None):
            w = (weight_override if weight_override is not None
                 else conv.weight.data().asnumpy())
            if bias_override is not None:
                b = bias_override
            else:
                b = None if conv.bias is None else conv.bias.data().asnumpy()
            super().__init__(w, b, getattr(conv, "act", None))
            self._kwargs = dict(conv._kwargs)

        def forward(self, x):
            qx, xmn, xmx = self._quantize_in(x)
            k = self._kwargs
            acc, omn, omx = _conv(qx, self._wq, None, xmn, xmx, self._wmn,
                                  self._wmx, kernel=k["kernel"],
                                  stride=k["stride"], pad=k["pad"],
                                  dilate=k["dilate"],
                                  num_filter=k["num_filter"], no_bias=True)
            y = _deq(acc, omn, omx)
            if self._b is not None:
                y = y + self._b.reshape((1, -1) + (1,) * (len(y.shape) - 2))
            return y if self._act is None else self._act(y)

    return _Int8Dense, _Int8Conv


def quantize_net(network, quantized_dtype="int8", quantize_mode="full",
                 exclude_layers=None, exclude_layers_match=None,
                 calib_data=None, data_shapes=None, calib_mode="none",
                 num_calib_examples=None, ctx=None, logger=logging):
    """Gluon-path post-training quantization (reference quantization.py:700
    quantize_net): Dense/Conv2D blocks are swapped for int8 blocks that run
    ``quantize_v2 -> int8 matmul/conv (int32 accumulate) -> dequantize``.
    With ``calib_data`` the activation ranges are frozen from calibration
    forwards (``calib_mode='naive'``); otherwise quantization is dynamic
    per batch. Unsupported layers (grouped convs, exclusions) stay float."""
    from ..gluon import nn as gnn
    _Int8Dense, _Int8Conv = _int8_blocks()
    count = 0
    exclude = set(exclude_layers or [])
    match = tuple(exclude_layers_match or ())
    swapped = []

    def _excluded(name):
        return name in exclude or any(m in name for m in match)

    class _FoldedIdentity(_int8_identity_base()):
        """Placeholder for a BatchNorm folded into the preceding conv
        (reference quantize_graph_pass.cc folds BN before quantizing so
        no float normalization sits between int8 layers)."""

        def forward(self, x):
            return x

    def _fold_bn(conv, bn):
        """Return (weight', bias') with the BN's inference transform
        folded into the conv: w' = w * g/sqrt(v+eps) per out-channel,
        b' = beta + (b - mean) * g/sqrt(v+eps)."""
        w = conv.weight.data().asnumpy().astype(np.float32)
        b = (np.zeros(w.shape[0], np.float32) if conv.bias is None
             else conv.bias.data().asnumpy().astype(np.float32))
        gamma = bn.gamma.data().asnumpy().astype(np.float32)
        beta = bn.beta.data().asnumpy().astype(np.float32)
        mean = bn.running_mean.data().asnumpy().astype(np.float32)
        var = bn.running_var.data().asnumpy().astype(np.float32)
        eps = bn._kwargs.get("eps", 1e-5)
        scale = gamma / np.sqrt(var + eps)
        w2 = w * scale.reshape((-1,) + (1,) * (w.ndim - 1))
        b2 = beta + (b - mean) * scale
        return w2, b2

    def visit(block):
        nonlocal count
        items = list(block._children.items())
        # pass 1: fold Conv2D -> BatchNorm adjacencies (inference-mode BN
        # is an affine transform absorbable into the conv; keeping it
        # float between int8 layers was the measured perf pessimization,
        # PERF.md round-2 int8 study). Registration order == dataflow
        # ONLY inside Sequential containers, and a conv with a fused
        # activation computes act BEFORE the BN, so neither folds.
        folds = {}
        folded_keys = set()
        sequential = isinstance(block, (gnn.HybridSequential,
                                        gnn.Sequential))
        if sequential:
            for (k1, c1), (k2, c2) in zip(items, items[1:]):
                if (isinstance(c1, gnn.Conv2D)
                        and isinstance(c2, gnn.BatchNorm)
                        and getattr(c1, "act", None) is None
                        and c1.weight._data is not None
                        and c2.gamma._data is not None
                        and c1._kwargs.get("num_group", 1) == 1
                        and not _excluded(c1.name)
                        and not _excluded(c2.name)):
                    folds[k1] = (c1, c2, k2)
                    folded_keys.add(k2)
        for key, child in items:
            qb = None
            if key in folds:
                c1, c2, k2 = folds[key]
                w2, b2 = _fold_bn(c1, c2)
                qb = _Int8Conv(c1, weight_override=w2, bias_override=b2)
                ident = _FoldedIdentity()
                block._children[k2] = ident
                if getattr(block, k2, None) is c2:
                    object.__setattr__(block, k2, ident)
            elif _excluded(child.name):
                pass
            elif isinstance(child, gnn.Dense) and \
                    child.weight._data is not None:
                qb = _Int8Dense(child)
            elif isinstance(child, gnn.Conv2D) and \
                    child.weight._data is not None and \
                    child._kwargs.get("num_group", 1) == 1:
                qb = _Int8Conv(child)
            if qb is not None:
                block._children[key] = qb
                if getattr(block, key, None) is child:
                    object.__setattr__(block, key, qb)
                swapped.append(qb)
                count += 1
            elif key not in folded_keys:
                visit(child)

    visit(network)
    if calib_data is not None and calib_mode != "none":
        for qb in swapped:
            qb._calibrating = True
        seen = 0
        for batch in calib_data:
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            network(x if isinstance(x, NDArray) else NDArray(
                jnp.asarray(np.asarray(x))))
            seen += x.shape[0]
            if num_calib_examples and seen >= num_calib_examples:
                break
        for qb in swapped:
            qb._calibrating = False
            qb._freeze_calibration()
        logger.info("calibrated %d layers on %d examples", count, seen)
    logger.info("quantize_net: %d layers swapped to int8", count)
    return network
