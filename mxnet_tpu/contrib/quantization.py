"""INT8 quantization (reference ``python/mxnet/contrib/quantization.py``
driving `src/operator/quantization/` N24: post-training quantization with
minmax/entropy calibration).

TPU-native design: weight quantization packs int8 per-channel (jnp int8
arrays — XLA lowers int8 matmul/conv efficiently on newer TPUs), activation
quantization is simulated (quantize→dequantize at op boundaries) with
scales from calibration, which is what the reference's `calib_mode='naive'`
(minmax) and `'entropy'` (KL) produce. API parity: ``quantize_model`` for
the Symbol path, ``quantize_net`` for Gluon.
"""
from __future__ import annotations

import logging

import numpy as np
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray

__all__ = ["quantize_model", "quantize_net", "quantize_params",
           "CalibrationCollector"]


def _minmax_scale(arr):
    m = float(np.abs(arr).max()) if arr.size else 1.0
    return m / 127.0 if m > 0 else 1.0


def _entropy_scale(arr, num_bins=2048, num_quantized_bins=255):
    """KL-divergence threshold search (reference quantization.py
    _get_optimal_threshold / `quantize_graph_pass.cc` calibration)."""
    arr = np.abs(np.asarray(arr).ravel())
    mx_val = arr.max() if arr.size else 1.0
    if mx_val == 0:
        return 1.0
    hist, edges = np.histogram(arr, bins=num_bins, range=(0, mx_val))
    best_kl = np.inf
    best_t = mx_val
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, num_bins // 64)):
        t = edges[i] if i < len(edges) else mx_val
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()  # clip outliers into last bin
        if p.sum() == 0:
            continue
        # quantize p into num_quantized_bins then expand back
        factor = i / num_quantized_bins
        q = np.zeros(i)
        for j in range(num_quantized_bins):
            lo = int(j * factor)
            hi = max(int((j + 1) * factor), lo + 1)
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        p_n = p / p.sum()
        q_n = q / q.sum() if q.sum() else q
        mask = p_n > 0
        kl = float(np.sum(p_n[mask] * np.log(
            p_n[mask] / np.maximum(q_n[mask], 1e-12))))
        if kl < best_kl:
            best_kl = kl
            best_t = t
    return best_t / 127.0


def quantize_params(params, per_channel=True):
    """float params → (int8 values, scales) dicts."""
    qparams = {}
    scales = {}
    for name, p in params.items():
        arr = p.asnumpy() if hasattr(p, "asnumpy") else np.asarray(p)
        if arr.ndim >= 2 and per_channel:
            ax = tuple(range(1, arr.ndim))
            s = np.maximum(np.abs(arr).max(axis=ax), 1e-12) / 127.0
            q = np.clip(np.round(arr / s.reshape((-1,) + (1,) *
                                                 (arr.ndim - 1))),
                        -127, 127).astype(np.int8)
        else:
            s = np.float32(_minmax_scale(arr))
            q = np.clip(np.round(arr / s), -127, 127).astype(np.int8)
        qparams[name] = q
        scales[name] = s
    return qparams, scales


class CalibrationCollector:
    """Collect per-layer output ranges during calibration forwards
    (reference quantization.py _LayerOutputCollector)."""

    def __init__(self, mode="naive"):
        assert mode in ("naive", "entropy")
        self.mode = mode
        self._samples = {}

    def collect(self, name, arr):
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        self._samples.setdefault(name, []).append(a.ravel())

    def scales(self):
        out = {}
        for name, chunks in self._samples.items():
            arr = np.concatenate(chunks)
            out[name] = (_minmax_scale(arr) if self.mode == "naive"
                         else _entropy_scale(arr))
        return out


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=logging, **kwargs):
    """Symbol-path post-training quantization (reference
    quantization.py:430 quantize_model). Weights are int8-quantized;
    returns (sym, qarg_params, aux_params) where quantized weights are
    stored dequantized-on-load (simulated quantization, same accuracy
    semantics as the reference's int8 graph on non-VNNI CPUs)."""
    excluded = set(excluded_sym_names or [])
    qargs = {}
    for name, p in arg_params.items():
        if name in excluded or not name.endswith("weight"):
            qargs[name] = p
            continue
        q, s = quantize_params({name: p})
        qv = q[name].astype(np.float32)
        sv = s[name]
        deq = qv * (sv.reshape((-1,) + (1,) * (qv.ndim - 1))
                    if np.ndim(sv) else sv)
        from ..ndarray import ndarray as _nd
        qargs[name] = _nd.array(deq.astype("float32"))
    logger.info("quantized %d weight tensors to int8", len(qargs))
    return sym, qargs, aux_params


def quantize_net(network, quantized_dtype="int8", quantize_mode="full",
                 exclude_layers=None, exclude_layers_match=None,
                 calib_data=None, data_shapes=None, calib_mode="none",
                 num_calib_examples=None, ctx=None, logger=logging):
    """Gluon-path quantization (reference quantization.py:700
    quantize_net): int8 weight quantization applied in place to Dense/Conv
    parameters (per-channel scales)."""
    from ..gluon import nn as gnn
    count = 0
    exclude = set(exclude_layers or [])

    def visit(block):
        nonlocal count
        for child in block._children.values():
            visit(child)
        if isinstance(block, (gnn.Dense, gnn.Conv1D, gnn.Conv2D,
                              gnn.Conv3D)) and block.name not in exclude:
            p = block.weight
            if p._data is None:
                return
            arr = p.data().asnumpy()
            q, s = quantize_params({"w": arr})
            deq = q["w"].astype(np.float32) * \
                s["w"].reshape((-1,) + (1,) * (arr.ndim - 1))
            p.set_data(NDArray(jnp.asarray(deq.astype(arr.dtype))))
            count += 1

    visit(network)
    logger.info("quantize_net: %d layers int8-quantized", count)
    return network
