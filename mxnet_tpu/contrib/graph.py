"""Graph ops over CSR adjacency matrices.

Role parity: reference ``src/operator/contrib/dgl_graph.cc`` (edge_id,
dgl_adjacency, dgl_subgraph — the DGL v0.x integration ops) and
``contrib/nnz.cc`` (getnnz). These are host-side graph *preparation*
utilities in the reference too (CPU-only FComputeEx kernels feeding the
sampler pipeline), so the TPU build keeps them eager on host numpy over
the CSR payloads — they never appear inside a jitted step.
"""
from __future__ import annotations

import numpy as np

from ..ndarray.ndarray import NDArray
from ..ndarray.sparse import CSRNDArray

__all__ = ["edge_id", "getnnz", "dgl_adjacency", "dgl_subgraph"]


def _csr_parts(csr):
    if not isinstance(csr, CSRNDArray):
        raise TypeError("expected a CSRNDArray, got %r" % type(csr))
    d, i, p = csr._payload()
    return (np.asarray(d), np.asarray(i, dtype=np.int64),
            np.asarray(p, dtype=np.int64))


def edge_id(data, u, v):
    """Edge data value for each (u[i], v[i]) pair, -1 when absent
    (reference dgl_graph.cc _contrib_edge_id)."""
    d, idx, ptr = _csr_parts(data)
    uu = np.asarray(u.asnumpy() if isinstance(u, NDArray) else u,
                    dtype=np.int64)
    vv = np.asarray(v.asnumpy() if isinstance(v, NDArray) else v,
                    dtype=np.int64)
    out = np.full(uu.shape, -1.0, dtype=np.float32)
    for k, (a, b) in enumerate(zip(uu.ravel(), vv.ravel())):
        cols = idx[ptr[a]:ptr[a + 1]]
        hit = np.nonzero(cols == b)[0]
        if hit.size:
            out.ravel()[k] = d[ptr[a] + hit[0]]
    return NDArray(out)


def getnnz(data, axis=None):
    """Stored-value count of a CSR matrix, total or per row/column
    (reference contrib/nnz.cc)."""
    d, idx, ptr = _csr_parts(data)
    if axis is None:
        return NDArray(np.asarray(len(d), dtype=np.int64))
    if axis == 1:
        return NDArray(np.diff(ptr).astype(np.int64))
    if axis == 0:
        counts = np.zeros(data.shape[1], dtype=np.int64)
        np.add.at(counts, idx, 1)
        return NDArray(counts)
    raise ValueError("axis must be None, 0 or 1")


def dgl_adjacency(data):
    """Adjacency CSR with all-ones values and the same sparsity pattern
    (reference dgl_graph.cc _contrib_dgl_adjacency)."""
    d, idx, ptr = _csr_parts(data)
    return CSRNDArray(np.ones_like(np.asarray(d), dtype=np.float32),
                      idx, ptr, data.shape)


def dgl_subgraph(graph, *vids, return_mapping=False):
    """Vertex-induced subgraphs of a CSR graph (reference dgl_graph.cc
    _contrib_dgl_subgraph): for each vertex-id array, the rows/cols
    restricted to those vertices, renumbered to the induced order. With
    ``return_mapping`` also yields same-pattern CSRs whose values are the
    originating edge positions in the parent graph."""
    d, idx, ptr = _csr_parts(graph)
    outs, maps = [], []
    for vid in vids:
        v = np.asarray(vid.asnumpy() if isinstance(vid, NDArray) else vid,
                       dtype=np.int64).ravel()
        v = v[v >= 0]
        renum = -np.ones(graph.shape[0], dtype=np.int64)
        renum[v] = np.arange(v.size)
        sub_data, sub_idx, sub_map = [], [], []
        sub_ptr = [0]
        for r in v:
            cols = idx[ptr[r]:ptr[r + 1]]
            keep = renum[cols] >= 0
            sub_idx.extend(renum[cols[keep]])
            sub_data.extend(d[ptr[r]:ptr[r + 1]][keep])
            sub_map.extend((ptr[r] + np.nonzero(keep)[0]).tolist())
            sub_ptr.append(len(sub_idx))
        shape = (v.size, v.size)
        outs.append(CSRNDArray(np.asarray(sub_data, dtype=np.float32),
                               np.asarray(sub_idx, dtype=np.int64),
                               np.asarray(sub_ptr, dtype=np.int64), shape))
        maps.append(CSRNDArray(np.asarray(sub_map, dtype=np.float32),
                               np.asarray(sub_idx, dtype=np.int64),
                               np.asarray(sub_ptr, dtype=np.int64), shape))
    res = outs + (maps if return_mapping else [])
    return res[0] if len(res) == 1 else tuple(res)
