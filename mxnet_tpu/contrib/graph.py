"""Graph ops over CSR adjacency matrices.

Role parity: reference ``src/operator/contrib/dgl_graph.cc`` (edge_id,
dgl_adjacency, dgl_subgraph — the DGL v0.x integration ops) and
``contrib/nnz.cc`` (getnnz). These are host-side graph *preparation*
utilities in the reference too (CPU-only FComputeEx kernels feeding the
sampler pipeline), so the TPU build keeps them eager on host numpy over
the CSR payloads — they never appear inside a jitted step.
"""
from __future__ import annotations

import numpy as np

from ..ndarray.ndarray import NDArray
from ..ndarray.sparse import CSRNDArray

__all__ = ["edge_id", "getnnz", "dgl_adjacency", "dgl_subgraph",
           "dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample", "dgl_graph_compact"]


def _csr_parts(csr):
    if not isinstance(csr, CSRNDArray):
        raise TypeError("expected a CSRNDArray, got %r" % type(csr))
    d, i, p = csr._payload()
    return (np.asarray(d), np.asarray(i, dtype=np.int64),
            np.asarray(p, dtype=np.int64))


def edge_id(data, u, v):
    """Edge data value for each (u[i], v[i]) pair, -1 when absent
    (reference dgl_graph.cc _contrib_edge_id)."""
    d, idx, ptr = _csr_parts(data)
    uu = np.asarray(u.asnumpy() if isinstance(u, NDArray) else u,
                    dtype=np.int64)
    vv = np.asarray(v.asnumpy() if isinstance(v, NDArray) else v,
                    dtype=np.int64)
    out = np.full(uu.shape, -1.0, dtype=np.float32)
    for k, (a, b) in enumerate(zip(uu.ravel(), vv.ravel())):
        cols = idx[ptr[a]:ptr[a + 1]]
        hit = np.nonzero(cols == b)[0]
        if hit.size:
            out.ravel()[k] = d[ptr[a] + hit[0]]
    return NDArray(out)


def getnnz(data, axis=None):
    """Stored-value count of a CSR matrix, total or per row/column
    (reference contrib/nnz.cc)."""
    d, idx, ptr = _csr_parts(data)
    if axis is None:
        return NDArray(np.asarray(len(d), dtype=np.int64))
    if axis == 1:
        return NDArray(np.diff(ptr).astype(np.int64))
    if axis == 0:
        counts = np.zeros(data.shape[1], dtype=np.int64)
        np.add.at(counts, idx, 1)
        return NDArray(counts)
    raise ValueError("axis must be None, 0 or 1")


def dgl_adjacency(data):
    """Adjacency CSR with all-ones values and the same sparsity pattern
    (reference dgl_graph.cc _contrib_dgl_adjacency)."""
    d, idx, ptr = _csr_parts(data)
    return CSRNDArray(np.ones_like(np.asarray(d), dtype=np.float32),
                      idx, ptr, data.shape)


def dgl_subgraph(graph, *vids, return_mapping=False):
    """Vertex-induced subgraphs of a CSR graph (reference dgl_graph.cc
    _contrib_dgl_subgraph): for each vertex-id array, the rows/cols
    restricted to those vertices, renumbered to the induced order. With
    ``return_mapping`` also yields same-pattern CSRs whose values are the
    originating edge positions in the parent graph."""
    d, idx, ptr = _csr_parts(graph)
    outs, maps = [], []
    for vid in vids:
        v = np.asarray(vid.asnumpy() if isinstance(vid, NDArray) else vid,
                       dtype=np.int64).ravel()
        v = v[v >= 0]
        renum = -np.ones(graph.shape[0], dtype=np.int64)
        renum[v] = np.arange(v.size)
        sub_data, sub_idx, sub_map = [], [], []
        sub_ptr = [0]
        for r in v:
            cols = idx[ptr[r]:ptr[r + 1]]
            keep = renum[cols] >= 0
            sub_idx.extend(renum[cols[keep]])
            sub_data.extend(d[ptr[r]:ptr[r + 1]][keep])
            sub_map.extend((ptr[r] + np.nonzero(keep)[0]).tolist())
            sub_ptr.append(len(sub_idx))
        shape = (v.size, v.size)
        outs.append(CSRNDArray(np.asarray(sub_data, dtype=np.float32),
                               np.asarray(sub_idx, dtype=np.int64),
                               np.asarray(sub_ptr, dtype=np.int64), shape))
        maps.append(CSRNDArray(np.asarray(sub_map, dtype=np.float32),
                               np.asarray(sub_idx, dtype=np.int64),
                               np.asarray(sub_ptr, dtype=np.int64), shape))
    res = outs + (maps if return_mapping else [])
    return res[0] if len(res) == 1 else tuple(res)


def _neighbor_sample(graph, seeds, num_hops, num_neighbor,
                     max_num_vertices, prob=None, rng=None):
    d, idx, ptr = _csr_parts(graph)
    rng = rng or np.random
    pv = None if prob is None else np.asarray(
        prob.asnumpy() if isinstance(prob, NDArray) else prob,
        dtype=np.float64)
    outs = []
    for seed in seeds:
        sv = np.asarray(seed.asnumpy() if isinstance(seed, NDArray)
                        else seed, dtype=np.int64).ravel()
        layer = {int(v): 0 for v in sv}
        frontier = list(layer)
        edges = []
        for hop in range(1, num_hops + 1):
            nxt = []
            for v in frontier:
                cols = idx[ptr[v]:ptr[v + 1]]
                vals = d[ptr[v]:ptr[v + 1]]
                if cols.size == 0:
                    continue
                if pv is None:
                    k = min(int(num_neighbor), cols.size)
                    pick = rng.choice(cols.size, size=k, replace=False)
                else:
                    w = pv[cols]
                    nz = int((w > 0).sum())
                    if nz == 0:
                        continue
                    # without-replacement draws need >= k positive-prob
                    # entries or np.random.choice raises
                    k = min(int(num_neighbor), nz)
                    pick = rng.choice(cols.size, size=k, replace=False,
                                      p=w / w.sum())
                for j in pick:
                    nb = int(cols[j])
                    edges.append((v, nb, vals[j]))
                    if nb not in layer and len(layer) < max_num_vertices:
                        layer[nb] = hop
                        nxt.append(nb)
            frontier = nxt
        verts = np.array(sorted(layer), dtype=np.int64)
        n = verts.size
        vset = set(verts.tolist())
        varr = np.zeros(max_num_vertices + 1, np.int64)
        varr[:n] = verts
        varr[-1] = n
        larr = np.zeros(max_num_vertices, np.int64)
        larr[:n] = [layer[int(v)] for v in verts]
        # sampled-edge CSR in ORIGINAL vertex numbering, graph-shaped
        rows = {}
        for (s, t, val) in edges:
            if s in vset and t in vset:
                rows.setdefault(s, {})[t] = val
        sd, si = [], []
        sp = [0]
        for r in range(graph.shape[0]):
            cols = sorted(rows.get(r, {}))
            si.extend(cols)
            sd.extend(rows[r][c] for c in cols)
            sp.append(len(si))
        outs.append((NDArray(varr),
                     CSRNDArray(np.asarray(sd, dtype=np.float32),
                                np.asarray(si, dtype=np.int64),
                                np.asarray(sp, dtype=np.int64),
                                graph.shape),
                     NDArray(larr)))
    flat = [o[0] for o in outs] + [o[1] for o in outs] + \
        [o[2] for o in outs]
    return tuple(flat)


def dgl_csr_neighbor_uniform_sample(csr, *seeds, num_args=0, num_hops=1,
                                    num_neighbor=2, max_num_vertices=100):
    """Uniform neighbor sampling for DGL (reference dgl_graph.cc): per seed
    array returns (vertices[max+1] with the count in the last slot, the
    sampled-edge CSR in original numbering, per-vertex hop layers)."""
    return _neighbor_sample(csr, seeds, int(num_hops), int(num_neighbor),
                            int(max_num_vertices))


def dgl_csr_neighbor_non_uniform_sample(csr, probability, *seeds,
                                        num_args=0, num_hops=1,
                                        num_neighbor=2,
                                        max_num_vertices=100):
    """Probability-weighted variant of the neighbor sampler."""
    return _neighbor_sample(csr, seeds, int(num_hops), int(num_neighbor),
                            int(max_num_vertices), prob=probability)


def dgl_graph_compact(*args, graph_sizes=(), return_mapping=False):
    """Strip the empty tail rows/columns a sampler-produced CSR carries and
    renumber to the sampled-vertex order (reference dgl_graph.cc
    _contrib_dgl_graph_compact). ``args`` = sampled CSRs followed by their
    vertex arrays; ``graph_sizes`` = actual vertex counts."""
    n = len(args) // 2
    graphs, vids = args[:n], args[n:]
    if not isinstance(graph_sizes, (tuple, list)):
        graph_sizes = (graph_sizes,)
    outs = []
    for g, v, size in zip(graphs, vids, graph_sizes):
        d, idx, ptr = _csr_parts(g)
        verts = np.asarray(v.asnumpy() if isinstance(v, NDArray) else v,
                           dtype=np.int64).ravel()[:int(size)]
        renum = -np.ones(g.shape[0], dtype=np.int64)
        renum[verts] = np.arange(verts.size)
        sd, si = [], []
        sp = [0]
        for r in verts:
            cols = idx[ptr[r]:ptr[r + 1]]
            keep = renum[cols] >= 0
            order = np.argsort(renum[cols[keep]])
            si.extend(renum[cols[keep]][order])
            sd.extend(d[ptr[r]:ptr[r + 1]][keep][order])
            sp.append(len(si))
        outs.append(CSRNDArray(np.asarray(sd, dtype=np.float32),
                               np.asarray(si, dtype=np.int64),
                               np.asarray(sp, dtype=np.int64),
                               (verts.size, verts.size)))
    return outs[0] if len(outs) == 1 else tuple(outs)
