"""Dynamic loss scaler (reference ``contrib/amp/loss_scaler.py``): grow the
scale every `scale_window` clean steps, halve it on overflow. Needed only
for true fp16; bf16 on TPU keeps scale at 1.

The sharded-trainer path fuses this whole state machine into the compiled
step (``resilience/guardrails.py`` ``GuardedStep``); this host-side class
remains for the eager/Module path — with :meth:`has_overflow` now doing
ONE fused device-side all-finite reduction and a single scalar readback
instead of the reference's blocking ``asnumpy()`` per gradient per step.
"""
from __future__ import annotations

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (reference loss_scaler.py).

        The reduction over every gradient runs on device (one fused
        ``isfinite``/``all`` chain, see ``guardrails.all_finite``); the
        only device→host traffic is the final scalar bool — per STEP, not
        per gradient."""
        grads = []
        for param in params:
            if param.grad_req != "null":
                for grad in param.list_grad():
                    grads.append(grad._data)
        if not grads:
            return False
        from ...resilience.guardrails import all_finite
        return not bool(all_finite(grads))

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1)
            self._unskipped = 0
        else:
            self._unskipped += 1
        if self._unskipped == self._scale_window:
            self.loss_scale *= self._scale_factor
            self._unskipped = 0
