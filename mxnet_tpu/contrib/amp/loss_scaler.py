"""Dynamic loss scaler (reference ``contrib/amp/loss_scaler.py``): grow the
scale every `scale_window` clean steps, halve it on overflow. Needed only
for true fp16; bf16 on TPU keeps scale at 1."""
from __future__ import annotations

import numpy as _np

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (reference loss_scaler.py)."""
        for param in params:
            if param.grad_req != "null":
                for grad in param.list_grad():
                    g = grad.asnumpy()
                    if not _np.isfinite(g).all():
                        return True
        return False

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1)
            self._unskipped = 0
        else:
            self._unskipped += 1
        if self._unskipped == self._scale_window:
            self.loss_scale *= self._scale_factor
            self._unskipped = 0
