"""AMP core (reference ``python/mxnet/contrib/amp/amp.py``: init :251
monkey-patches op namespaces to insert amp_cast; convert_model :509 runs the
C++ low_precision_pass).

TPU-native: the target dtype is bfloat16 — same exponent range as fp32, so
NO loss scaling is required (the reference's fp16 machinery exists because
of fp16's narrow exponent). `init()` flips a global policy consumed by
`convert_hybrid_block`/`convert_model` (cast params + inputs to bf16, keep
normalization/softmax/loss in fp32 — the lp16/fp32 op lists below mirror
the reference's amp_lists). The LossScaler is provided for API parity and
for true fp16 use, with dynamic scaling semantics preserved.
"""
from __future__ import annotations

import contextlib
import logging

import numpy as _np

_amp_initialized = [False]
_target_dtype = ["bfloat16"]

# role of the reference amp_lists (lists.symbol_fp16.py): ops that stay fp32
FP32_OPS = ["BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "softmax",
            "log_softmax", "SoftmaxOutput", "softmax_cross_entropy", "norm",
            "mean", "sum", "erfinv", "_ctc_loss"]
LP16_OPS = ["FullyConnected", "Convolution", "Deconvolution", "dot",
            "batch_dot", "matmul", "_contrib_dot_product_attention",
            "_rnn_scan_layer"]


def list_lp16_ops(target_dtype="bfloat16"):
    return list(LP16_OPS)


def list_fp32_ops(target_dtype="bfloat16"):
    return list(FP32_OPS)


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """reference amp.py:251. On TPU bf16 is the only sensible target; fp16
    is accepted and treated identically (XLA handles it)."""
    if _amp_initialized[0]:
        return
    if hasattr(target_dtype, "name"):
        target_dtype = target_dtype.name
    assert str(target_dtype) in ("float16", "bfloat16"), \
        "AMP target must be float16 or bfloat16"
    _target_dtype[0] = "bfloat16"  # TPU: always bf16 compute
    _amp_initialized[0] = True
    logging.info("AMP init: using %s compute on TPU (loss scaling not "
                 "required for bf16)", _target_dtype[0])


def init_trainer(trainer):
    """reference amp.py — wires the loss scaler into a Trainer. bf16 needs
    no scaling; kept as a no-op hook for fp16-style workflows."""
    trainer._amp_loss_scaler = LossScalerRef()
    return trainer


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Context manager yielding the scaled loss (reference amp.py:
    ``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``).
    With bf16 the scale is 1 and this is the identity."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(optimizer_or_trainer):
    scaler = getattr(optimizer_or_trainer, "_amp_loss_scaler", None)
    if scaler is not None and scaler.loss_scale != 1.0:
        for p in optimizer_or_trainer._params:
            if p.grad_req != "null":
                for g in p.list_grad():
                    g[:] = g / scaler.loss_scale


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  target_dtype_ops=None, fp32_ops=None,
                  conditional_fp32_ops=None, excluded_sym_names=None,
                  cast_optional_params=False):
    """Symbolic AMP conversion (reference amp.py:509 →
    `src/nnvm/low_precision_pass.cc`). Under XLA the graph pass reduces to
    casting the parameters — XLA propagates the compute dtype."""
    new_args = {k: _cast_param(v, target_dtype) for k, v in
                arg_params.items()}
    new_aux = {k: v for k, v in aux_params.items()}  # aux stays fp32
    return sym, new_args, new_aux


def convert_hybrid_block(block, target_dtype="bfloat16", **kwargs):
    """Cast a Gluon block for bf16 compute (reference amp.py
    convert_hybrid_block)."""
    block.cast(target_dtype)
    return block


def _cast_param(arr, dtype):
    name = getattr(arr, "dtype", None)
    return arr.astype(dtype) if hasattr(arr, "astype") else arr


class LossScalerRef:
    loss_scale = 1.0


from .loss_scaler import LossScaler  # noqa: E402,F401
