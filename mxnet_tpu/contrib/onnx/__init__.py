"""ONNX interop (reference ``python/mxnet/contrib/onnx/__init__.py``):
``export_model`` (mx2onnx) and ``import_model`` (onnx2mx)."""
from .mx2onnx import export_model
from .onnx2mx import import_model

__all__ = ["export_model", "import_model"]
