"""Minimal ONNX protobuf wire-format codec.

The image ships no ``onnx`` package (and no protoc schema for it), so this
module encodes/decodes the subset of the ONNX ModelProto schema the
mx2onnx/onnx2mx converters need, straight in the protobuf wire format
(varint/length-delimited — https://protobuf.dev/programming-guides/encoding
semantics; field numbers from the public onnx.proto3 schema). Files
written here load in onnxruntime/netron; files produced by standard onnx
tooling parse back as long as they stay within the supported field set.

Role parity: reference ``python/mxnet/contrib/onnx`` builds the same
messages via the installed onnx package.
"""
from __future__ import annotations

import struct

import numpy as _np

# TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64, BOOL, FLOAT16, DOUBLE = \
    1, 2, 3, 6, 7, 9, 10, 11

NP_TO_ONNX = {
    _np.dtype(_np.float32): FLOAT,
    _np.dtype(_np.uint8): UINT8,
    _np.dtype(_np.int8): INT8,
    _np.dtype(_np.int32): INT32,
    _np.dtype(_np.int64): INT64,
    _np.dtype(_np.bool_): BOOL,
    _np.dtype(_np.float16): FLOAT16,
    _np.dtype(_np.float64): DOUBLE,
}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_GRAPH = 1, 2, 3, 4, 5
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


# ---------------------------------------------------------------- writer

def _varint(n):
    out = b""
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _tag(field, wire):
    return _varint((field << 3) | wire)


def w_varint(field, value):
    return _tag(field, 0) + _varint(int(value))


def w_bytes(field, data):
    if isinstance(data, str):
        data = data.encode("utf-8")
    return _tag(field, 2) + _varint(len(data)) + data


w_msg = w_bytes  # nested messages are length-delimited too


def w_packed_int64(field, values):
    body = b"".join(_varint(int(v)) for v in values)
    return _tag(field, 2) + _varint(len(body)) + body


def w_packed_float(field, values):
    body = struct.pack("<%df" % len(values), *values)
    return _tag(field, 2) + _varint(len(body)) + body


def tensor_proto(name, arr):
    arr = _np.ascontiguousarray(arr)
    dtype = NP_TO_ONNX[arr.dtype]
    out = w_packed_int64(1, arr.shape)          # dims
    out += w_varint(2, dtype)                   # data_type
    out += w_bytes(8, name)                     # name
    out += w_bytes(9, arr.tobytes())            # raw_data
    return out


def attribute(name, value):
    out = w_bytes(1, name)
    if isinstance(value, bool):
        out += w_varint(3, int(value)) + w_varint(20, A_INT)
    elif isinstance(value, int):
        out += w_varint(3, value) + w_varint(20, A_INT)
    elif isinstance(value, float):
        out += _tag(2, 5) + struct.pack("<f", value) + w_varint(20, A_FLOAT)
    elif isinstance(value, str):
        out += w_bytes(4, value) + w_varint(20, A_STRING)
    elif isinstance(value, bytes):
        out += w_bytes(4, value) + w_varint(20, A_STRING)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, int) for v in value):
            out += b"".join(w_varint(8, v) for v in value)
            out += w_varint(20, A_INTS)
        else:
            out += b"".join(_tag(7, 5) + struct.pack("<f", float(v))
                            for v in value)
            out += w_varint(20, A_FLOATS)
    else:
        raise TypeError("unsupported attribute %r=%r" % (name, value))
    return out


def node(op_type, inputs, outputs, name="", **attrs):
    out = b"".join(w_bytes(1, i) for i in inputs)
    out += b"".join(w_bytes(2, o) for o in outputs)
    out += w_bytes(3, name or outputs[0])
    out += w_bytes(4, op_type)
    out += b"".join(w_msg(5, attribute(k, v))
                    for k, v in attrs.items() if v is not None)
    return out


def value_info(name, shape, dtype=FLOAT):
    dims = b"".join(w_msg(1, w_varint(1, d)) for d in shape)
    tensor_type = w_varint(1, dtype) + w_msg(2, dims)
    type_proto = w_msg(1, tensor_type)
    return w_bytes(1, name) + w_msg(2, type_proto)


def graph(nodes, name, inputs, outputs, initializers):
    out = b"".join(w_msg(1, n) for n in nodes)
    out += w_bytes(2, name)
    out += b"".join(w_msg(5, t) for t in initializers)
    out += b"".join(w_msg(11, vi) for vi in inputs)
    out += b"".join(w_msg(12, vi) for vi in outputs)
    return out


def model(graph_bytes, opset=13, producer="mxnet_tpu"):
    out = w_varint(1, 8)                        # ir_version
    out += w_bytes(2, producer)                 # producer_name
    out += w_bytes(3, "0.1")                    # producer_version
    out += w_msg(7, graph_bytes)                # graph
    out += w_msg(8, w_varint(2, opset))         # opset_import (domain="")
    return out


# ---------------------------------------------------------------- reader

def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse(buf):
    """Parse a protobuf message into {field: [values]}; length-delimited
    fields stay bytes (caller re-parses nested messages)."""
    fields = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack("<I", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:
            val = struct.unpack("<Q", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError("unsupported wire type %d" % wire)
        fields.setdefault(field, []).append(val)
    return fields


def _unpack_varints(data):
    vals, pos = [], 0
    while pos < len(data):
        v, pos = _read_varint(data, pos)
        vals.append(v)
    return vals


def _signed(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_tensor(buf):
    f = parse(buf)
    dims = []
    for d in f.get(1, []):
        if isinstance(d, bytes):
            dims.extend(_signed(v) for v in _unpack_varints(d))
        else:
            dims.append(_signed(d))
    dtype = ONNX_TO_NP[f.get(2, [FLOAT])[0]]
    name = f.get(8, [b""])[0].decode("utf-8")
    if 9 in f:
        arr = _np.frombuffer(f[9][0], dtype=dtype).reshape(dims).copy()
    elif 4 in f:  # float_data — packed chunks and/or unpacked fixed32
        vals = []
        for item in f[4]:
            if isinstance(item, bytes):
                vals.extend(_np.frombuffer(item, "<f4").tolist())
            else:  # wire-type-5 value: raw uint32 bit pattern
                vals.append(struct.unpack("<f", struct.pack("<I", item))[0])
        arr = _np.array(vals, dtype=_np.float32).reshape(dims)
    elif 7 in f:  # int64_data
        vals = []
        for item in f[7]:
            if isinstance(item, bytes):
                vals.extend(_signed(v) for v in _unpack_varints(item))
            else:
                vals.append(_signed(item))
        arr = _np.array(vals, dtype=_np.int64).reshape(dims)
    else:
        arr = _np.zeros(dims, dtype=dtype)
    return name, arr


def parse_attribute(buf):
    f = parse(buf)
    name = f[1][0].decode("utf-8")
    atype = f.get(20, [None])[0]
    if atype == A_INT or (atype is None and 3 in f):
        return name, _signed(f[3][0])
    if atype == A_FLOAT or (atype is None and 2 in f):
        return name, struct.unpack("<f", struct.pack("<I", f[2][0]))[0]
    if atype == A_STRING or (atype is None and 4 in f):
        return name, f[4][0].decode("utf-8", "replace")
    if atype == A_INTS or (atype is None and 8 in f):
        vals = []
        for item in f.get(8, []):
            if isinstance(item, bytes):
                vals.extend(_signed(v) for v in _unpack_varints(item))
            else:
                vals.append(_signed(item))
        return name, vals
    if atype == A_FLOATS or (atype is None and 7 in f):
        vals = []
        for item in f.get(7, []):
            if isinstance(item, int):
                vals.append(struct.unpack("<f", struct.pack("<I", item))[0])
            else:
                vals.extend(_np.frombuffer(item, "<f4").tolist())
        return name, vals
    if atype == A_TENSOR or (atype is None and 5 in f):
        return name, parse_tensor(f[5][0])[1]
    return name, None


def parse_node(buf):
    f = parse(buf)
    return {
        "inputs": [b.decode("utf-8") for b in f.get(1, [])],
        "outputs": [b.decode("utf-8") for b in f.get(2, [])],
        "name": f.get(3, [b""])[0].decode("utf-8"),
        "op_type": f.get(4, [b""])[0].decode("utf-8"),
        "attrs": dict(parse_attribute(a) for a in f.get(5, [])),
    }


def parse_value_info(buf):
    f = parse(buf)
    name = f.get(1, [b""])[0].decode("utf-8")
    shape = []
    dtype = FLOAT
    if 2 in f:
        tp = parse(f[2][0])
        if 1 in tp:  # tensor_type
            tt = parse(tp[1][0])
            dtype = tt.get(1, [FLOAT])[0]
            if 2 in tt:
                sh = parse(tt[2][0])
                for dim in sh.get(1, []):
                    df = parse(dim)
                    shape.append(_signed(df[1][0]) if 1 in df else -1)
    return name, tuple(shape), dtype


def parse_graph(buf):
    f = parse(buf)
    return {
        "nodes": [parse_node(n) for n in f.get(1, [])],
        "name": f.get(2, [b""])[0].decode("utf-8"),
        "initializers": dict(parse_tensor(t) for t in f.get(5, [])),
        "inputs": [parse_value_info(v) for v in f.get(11, [])],
        "outputs": [parse_value_info(v) for v in f.get(12, [])],
    }


def parse_model(buf):
    f = parse(buf)
    if 7 not in f:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    return parse_graph(f[7][0])
