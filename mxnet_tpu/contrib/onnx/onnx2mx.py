"""ONNX -> Symbol graph import.

Role parity: reference ``python/mxnet/contrib/onnx/onnx2mx/import_model.py``
(+ _op_translations.py). Parses the ONNX file with the ``_proto`` codec
and rebuilds the graph over this framework's op registry, returning
(sym, arg_params, aux_params) like the reference.
"""
from __future__ import annotations

import numpy as _np

from . import _proto as P


def _attr_pad(pads):
    if not pads:
        return None
    n = len(pads) // 2
    if list(pads[:n]) != list(pads[n:]):
        raise NotImplementedError("asymmetric ONNX pads %s" % (pads,))
    return tuple(pads[:n])


def _check_no_auto_pad(a, name):
    ap = a.get("auto_pad")
    if ap and ap != "NOTSET":
        raise NotImplementedError(
            "auto_pad=%r on node %s is not supported; re-export the model "
            "with explicit pads" % (ap, name))


def import_model(model_file):
    """Load an ONNX model file -> (sym, arg_params, aux_params)
    (reference onnx2mx/import_model.py:30)."""
    from ... import symbol as S
    from ...ndarray import ndarray as _nd

    with open(model_file, "rb") as f:
        g = P.parse_model(f.read())

    inits = g["initializers"]
    values = {}          # onnx tensor name -> Symbol
    consumed_as_attr = set()
    arg_params, aux_params = {}, {}

    def val(name):
        if name in values:
            return values[name]
        v = S.var(name)
        values[name] = v
        return v

    for n, arr in inits.items():
        values[n] = S.var(n)

    for node in g["nodes"]:
        op = node["op_type"]
        a = node["attrs"]
        ins = node["inputs"]
        out = node["outputs"][0]
        name = node["name"] or out

        if op == "Conv":
            _check_no_auto_pad(a, name)
            kernel = tuple(a.get("kernel_shape"))
            sym = S.Convolution(
                val(ins[0]), *[val(i) for i in ins[1:]],
                kernel=kernel,
                stride=tuple(a.get("strides", (1,) * len(kernel))),
                dilate=tuple(a.get("dilations", (1,) * len(kernel))),
                pad=_attr_pad(a.get("pads")) or (0,) * len(kernel),
                num_filter=int(inits[ins[1]].shape[0]),
                num_group=int(a.get("group", 1)),
                no_bias=len(ins) < 3, name=name)
        elif op == "ConvTranspose":
            _check_no_auto_pad(a, name)
            kernel = tuple(a.get("kernel_shape"))
            sym = S.Deconvolution(
                val(ins[0]), *[val(i) for i in ins[1:]],
                kernel=kernel,
                stride=tuple(a.get("strides", (1,) * len(kernel))),
                dilate=tuple(a.get("dilations", (1,) * len(kernel))),
                pad=_attr_pad(a.get("pads")) or (0,) * len(kernel),
                num_filter=int(inits[ins[1]].shape[1]
                               * int(a.get("group", 1))),
                num_group=int(a.get("group", 1)),
                no_bias=len(ins) < 3, name=name)
        elif op == "Gemm":
            assert int(a.get("transB", 0)) == 1 and \
                int(a.get("transA", 0)) == 0, "only transB=1 Gemm supported"
            assert float(a.get("alpha", 1.0)) == 1.0 and \
                float(a.get("beta", 1.0)) == 1.0, \
                "only alpha=beta=1 Gemm supported"
            sym = S.FullyConnected(
                val(ins[0]), *[val(i) for i in ins[1:]],
                num_hidden=int(inits[ins[1]].shape[0]),
                no_bias=len(ins) < 3, flatten=False, name=name)
        elif op == "MatMul":
            sym = S.dot(val(ins[0]), val(ins[1]), name=name)
        elif op == "BatchNormalization":
            sym = S.BatchNorm(*[val(i) for i in ins],
                              eps=float(a.get("epsilon", 1e-5)),
                              momentum=float(a.get("momentum", 0.9)),
                              # ONNX semantics always apply the scale
                              # tensor; never ignore gamma on import
                              fix_gamma=False, name=name)
            for aux_in in ins[3:5]:
                if aux_in in inits:
                    aux_params[aux_in] = _nd.array(inits[aux_in])
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign",
                    "Exp", "Log", "Sqrt", "Abs", "Neg", "Identity",
                    "LogSoftmax"):
            fn = {"Relu": S.relu, "Sigmoid": S.sigmoid, "Tanh": S.tanh,
                  "Softplus": S.softrelu, "Softsign": S.softsign,
                  "Exp": S.exp, "Log": S.log, "Sqrt": S.sqrt,
                  "Abs": S.abs, "Neg": S.negative, "Identity": S.identity,
                  "LogSoftmax": S.log_softmax}[op]
            sym = fn(val(ins[0]), name=name)
        elif op == "LeakyRelu":
            sym = S.LeakyReLU(val(ins[0]), act_type="leaky",
                              slope=float(a.get("alpha", 0.01)), name=name)
        elif op == "Elu":
            sym = S.LeakyReLU(val(ins[0]), act_type="elu",
                              slope=float(a.get("alpha", 1.0)), name=name)
        elif op == "PRelu":
            sym = S.LeakyReLU(val(ins[0]), val(ins[1]), act_type="prelu",
                              name=name)
        elif op in ("MaxPool", "AveragePool"):
            _check_no_auto_pad(a, name)
            kernel = tuple(a.get("kernel_shape"))
            sym = S.Pooling(
                val(ins[0]), kernel=kernel,
                stride=tuple(a.get("strides", (1,) * len(kernel))),
                pad=_attr_pad(a.get("pads")) or (0,) * len(kernel),
                pooling_convention="full" if a.get("ceil_mode") else "valid",
                pool_type="max" if op == "MaxPool" else "avg",
                # ONNX spec default: exclude padding from the average
                count_include_pad=bool(a.get("count_include_pad", 0)),
                name=name)
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            sym = S.Pooling(val(ins[0]), global_pool=True, kernel=(1, 1),
                            pool_type="max" if op == "GlobalMaxPool"
                            else "avg", name=name)
        elif op == "Softmax":
            # opset<=12 semantics: coerce dims [axis..n) into ONE block and
            # normalize jointly (default axis=1). axis=-1 degenerates to a
            # plain last-axis softmax.
            axis = int(a.get("axis", 1))
            if axis == -1:
                sym = S.softmax(val(ins[0]), axis=-1, name=name)
            else:
                flat = S.reshape(val(ins[0]), shape=(0,) * axis + (-1,),
                                 name=name + "_flat2d")
                soft = S.softmax(flat, axis=-1, name=name + "_sm")
                sym = S.reshape_like(soft, val(ins[0]), name=name)
        elif op == "Dropout":
            sym = S.Dropout(val(ins[0]), p=float(a.get("ratio", 0.5)),
                            name=name)
        elif op == "Flatten":
            sym = S.Flatten(val(ins[0]), name=name)
        elif op == "Reshape":
            shape = inits.get(ins[1])
            if shape is None:
                raise NotImplementedError("dynamic Reshape shape input")
            consumed_as_attr.add(ins[1])
            sym = S.reshape(val(ins[0]),
                            shape=tuple(int(v) for v in shape), name=name)
        elif op == "Transpose":
            sym = S.transpose(val(ins[0]),
                              axes=tuple(a["perm"]) if a.get("perm")
                              else None, name=name)
        elif op == "Concat":
            sym = S.concat(*[val(i) for i in ins],
                           dim=int(a.get("axis", 1)), name=name)
        elif op == "Clip":
            def _bound(idx, default):
                # the spec encodes an omitted bound as a missing or
                # empty-string input
                if len(ins) <= idx or not ins[idx]:
                    return default
                if ins[idx] not in inits:
                    raise NotImplementedError(
                        "Clip bound %r comes from a computed tensor; only "
                        "initializer bounds are supported" % ins[idx])
                consumed_as_attr.add(ins[idx])
                return float(inits[ins[idx]])
            lo = _bound(1, -_np.inf)
            hi = _bound(2, _np.inf)
            sym = S.clip(val(ins[0]), a_min=lo, a_max=hi, name=name)
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": S.broadcast_add, "Sub": S.broadcast_sub,
                  "Mul": S.broadcast_mul, "Div": S.broadcast_div}[op]
            sym = fn(val(ins[0]), val(ins[1]), name=name)
        elif op == "ReduceMean":
            sym = S.mean(val(ins[0]),
                         axis=tuple(a["axes"]) if a.get("axes") else None,
                         # ONNX spec default keepdims=1
                         keepdims=bool(a.get("keepdims", 1)), name=name)
        else:
            raise NotImplementedError(
                "ONNX import: unsupported op %r (node %s)" % (op, name))
        values[out] = sym

    for n, arr in inits.items():
        if n in consumed_as_attr or n in aux_params:
            continue
        arg_params[n] = _nd.array(arr)

    out_syms = [values[n] for n, _, _ in g["outputs"]]
    sym = out_syms[0] if len(out_syms) == 1 else S.Group(out_syms)
    return sym, arg_params, aux_params
