"""Symbol graph -> ONNX export.

Role parity: reference ``python/mxnet/contrib/onnx/mx2onnx/export_model.py``
(+ _op_translations.py per-op converters). Targets opset 11. The ONNX
bytes are produced by the wire-format codec in ``_proto`` (no onnx
package in this environment).
"""
from __future__ import annotations

import numpy as _np

from . import _proto as P


def _ints(v, n=None):
    if v is None:
        return [1] * (n or 0)
    if isinstance(v, int):
        return [v] * (n or 1)
    return [int(x) for x in v]


def _pads2(pad, ndim=2):
    p = _ints(pad, ndim) if pad is not None else [0] * ndim
    return p + p  # symmetric begin+end


class _Ctx:
    def __init__(self, params=None):
        self.nodes = []
        self.initializers = []
        self.counter = 0
        self.params = params or {}
        self.skip_params = set()  # graph vars replaced by a converter

    def const(self, name, arr):
        self.initializers.append(P.tensor_proto(name, arr))
        return name

    def add(self, op_type, inputs, outputs, name="", **attrs):
        self.nodes.append(P.node(op_type, inputs, outputs, name, **attrs))


def _conv(ctx, name, ins, kw):
    kernel = _ints(kw.get("kernel"))
    attrs = dict(kernel_shape=kernel,
                 strides=_ints(kw.get("stride"), len(kernel)),
                 dilations=_ints(kw.get("dilate"), len(kernel)),
                 pads=_pads2(kw.get("pad"), len(kernel)),
                 group=int(kw.get("num_group", 1)))
    ctx.add("Conv", [i for i in ins if i is not None], [name], name, **attrs)


def _deconv(ctx, name, ins, kw):
    kernel = _ints(kw.get("kernel"))
    ctx.add("ConvTranspose", [i for i in ins if i is not None], [name], name,
            kernel_shape=kernel,
            strides=_ints(kw.get("stride"), len(kernel)),
            dilations=_ints(kw.get("dilate"), len(kernel)),
            pads=_pads2(kw.get("pad"), len(kernel)),
            group=int(kw.get("num_group", 1)))


def _fc(ctx, name, ins, kw):
    data = ins[0]
    if kw.get("flatten", True):
        flat = name + "_flat"
        ctx.add("Flatten", [data], [flat], flat, axis=1)
        data = flat
    gemm_in = [data, ins[1]] + ([ins[2]] if len(ins) > 2 and ins[2] else [])
    ctx.add("Gemm", gemm_in, [name], name, alpha=1.0, beta=1.0,
            transA=0, transB=1)


def _bn(ctx, name, ins, kw):
    ins = list(ins[:5])
    if kw.get("fix_gamma", False):
        # the op ignores the stored gamma when fix_gamma (ops/nn.py
        # BatchNorm); export a matching all-ones scale
        gamma = ctx.params.get(ins[1])
        if gamma is None:
            raise NotImplementedError(
                "cannot export fix_gamma BatchNorm %s: gamma %r is not a "
                "bound parameter" % (name, ins[1]))
        shape = gamma.shape if hasattr(gamma, "shape") else (len(gamma),)
        ctx.skip_params.add(ins[1])  # stored gamma is dead in the graph
        ins[1] = ctx.const(name + "_fixed_gamma",
                           _np.ones(shape, _np.float32))
    ctx.add("BatchNormalization", ins, [name], name,
            # the op's own default (ops/nn.py BatchNorm eps=1e-3)
            epsilon=float(kw.get("eps", 1e-3)),
            momentum=float(kw.get("momentum", 0.9)))


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _activation(ctx, name, ins, kw):
    ctx.add(_ACT[kw.get("act_type", "relu")], [ins[0]], [name], name)


def _pooling(ctx, name, ins, kw):
    ptype = kw.get("pool_type", "max")
    if kw.get("global_pool", False):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        ctx.add(op, [ins[0]], [name], name)
        return
    kernel = _ints(kw.get("kernel"))
    attrs = dict(kernel_shape=kernel,
                 strides=_ints(kw.get("stride"), len(kernel)),
                 pads=_pads2(kw.get("pad"), len(kernel)),
                 # 'full' convention == ONNX ceil_mode (opset >= 10)
                 ceil_mode=int(kw.get("pooling_convention",
                                      "valid") == "full"))
    if ptype == "max":
        ctx.add("MaxPool", [ins[0]], [name], name, **attrs)
    else:
        ctx.add("AveragePool", [ins[0]], [name], name,
                count_include_pad=int(kw.get("count_include_pad", True)),
                **attrs)


def _softmax(ctx, name, ins, kw):
    axis = int(kw.get("axis", -1))
    if axis != -1:
        # opset-11 Softmax attr means "flatten [axis..n)" — only the
        # last-axis case coincides with mxnet's per-axis semantics
        raise NotImplementedError(
            "opset-11 ONNX export supports softmax over the last axis "
            "only (node %s has axis=%d)" % (name, axis))
    ctx.add("Softmax", [ins[0]], [name], name, axis=-1)


def _dropout(ctx, name, ins, kw):
    ctx.add("Dropout", [ins[0]], [name], name, ratio=float(kw.get("p", 0.5)))


def _leaky(ctx, name, ins, kw):
    act = kw.get("act_type", "leaky")
    if act == "leaky":
        ctx.add("LeakyRelu", [ins[0]], [name], name,
                alpha=float(kw.get("slope", 0.25)))
    elif act == "elu":
        ctx.add("Elu", [ins[0]], [name], name,
                alpha=float(kw.get("slope", 0.25)))
    elif act == "prelu":
        ctx.add("PRelu", list(ins[:2]), [name], name)
    else:
        raise ValueError("cannot export LeakyReLU act_type=%s" % act)


def _reshape(ctx, name, ins, kw):
    shape = ctx.const(name + "_shape",
                      _np.array(kw.get("shape"), _np.int64))
    ctx.add("Reshape", [ins[0], shape], [name], name)


def _binop(onnx_op):
    def conv(ctx, name, ins, kw):
        ctx.add(onnx_op, list(ins[:2]), [name], name)
    return conv


def _scalar_op(onnx_op, rev=False):
    def conv(ctx, name, ins, kw):
        c = ctx.const(name + "_c",
                      _np.array(float(kw.get("scalar", 0.0)), _np.float32))
        inputs = [c, ins[0]] if rev else [ins[0], c]
        ctx.add(onnx_op, inputs, [name], name)
    return conv


def _unary(onnx_op):
    def conv(ctx, name, ins, kw):
        ctx.add(onnx_op, [ins[0]], [name], name)
    return conv


def _concat(ctx, name, ins, kw):
    ctx.add("Concat", list(ins), [name], name, axis=int(kw.get("dim", 1)))


def _transpose(ctx, name, ins, kw):
    ctx.add("Transpose", [ins[0]], [name], name,
            perm=_ints(kw.get("axes")) or None)


def _clip(ctx, name, ins, kw):
    lo = ctx.const(name + "_min",
                   _np.array(float(kw.get("a_min", 0.0)), _np.float32))
    hi = ctx.const(name + "_max",
                   _np.array(float(kw.get("a_max", 0.0)), _np.float32))
    ctx.add("Clip", [ins[0], lo, hi], [name], name)


def _mean(ctx, name, ins, kw):
    axes = kw.get("axis")
    ctx.add("ReduceMean", [ins[0]], [name], name,
            axes=_ints(axes) if axes is not None else None,
            keepdims=int(kw.get("keepdims", False)))


CONVERTERS = {
    "Convolution": _conv, "convolution": _conv,
    "Deconvolution": _deconv,
    "FullyConnected": _fc, "fully_connected": _fc,
    "BatchNorm": _bn, "batch_norm": _bn,
    "Activation": _activation, "activation": _activation,
    "Pooling": _pooling, "pooling": _pooling,
    "softmax": _softmax, "Softmax": _softmax, "SoftmaxOutput": _softmax,
    "log_softmax": _unary("LogSoftmax"),
    "Dropout": _dropout, "dropout": _dropout,
    "LeakyReLU": _leaky,
    "reshape": _reshape, "Reshape": _reshape,
    "Flatten": _unary("Flatten"), "flatten": _unary("Flatten"),
    "add": _binop("Add"), "elemwise_add": _binop("Add"),
    "broadcast_add": _binop("Add"), "_plus": _binop("Add"),
    "subtract": _binop("Sub"), "broadcast_sub": _binop("Sub"),
    "multiply": _binop("Mul"), "broadcast_mul": _binop("Mul"),
    "divide": _binop("Div"), "broadcast_div": _binop("Div"),
    "dot": _binop("MatMul"), "matmul": _binop("MatMul"),
    "_plus_scalar": _scalar_op("Add"),
    "_minus_scalar": _scalar_op("Sub"),
    "_rminus_scalar": _scalar_op("Sub", rev=True),
    "_mul_scalar": _scalar_op("Mul"),
    "_div_scalar": _scalar_op("Div"),
    "_rdiv_scalar": _scalar_op("Div", rev=True),
    "relu": _unary("Relu"), "sigmoid": _unary("Sigmoid"),
    "tanh": _unary("Tanh"), "exp": _unary("Exp"), "log": _unary("Log"),
    "sqrt": _unary("Sqrt"), "abs": _unary("Abs"),
    "negative": _unary("Neg"), "identity": _unary("Identity"),
    "_copy": _unary("Identity"),
    "concat": _concat, "Concat": _concat,
    "transpose": _transpose,
    "clip": _clip,
    "mean": _mean,
}


def export_model(sym, params, input_shape=None, input_type=_np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a Symbol + params to an ONNX file (reference
    mx2onnx/export_model.py:44 signature). ``input_shape`` is a list of
    shapes for the graph's data variables. Returns the file path."""
    from ...symbol.symbol import Symbol
    from ... import symbol as sym_mod
    if isinstance(sym, str):
        sym = sym_mod.load(sym)
    if not isinstance(sym, Symbol):
        raise TypeError("sym must be a Symbol or symbol file path")
    params = {k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k: v
              for k, v in (params or {}).items()}

    ctx = _Ctx(params)
    nodes = sym._toposort()
    out_names = {}  # (id(node), out_idx) -> onnx name
    inputs = []
    shapes_in = list(input_shape or [])

    pending_params = []  # emitted after the walk; converters may replace
    for n in nodes:
        name = n._name or "node%d" % ctx.counter
        ctx.counter += 1
        if n._op is None:
            if n._name in params:
                pending_params.append(n._name)
            else:
                shape = shapes_in.pop(0) if shapes_in else (1,)
                inputs.append(P.value_info(
                    n._name, shape, P.NP_TO_ONNX[_np.dtype(input_type)]))
            out_names[(id(n), 0)] = n._name
            continue
        conv = CONVERTERS.get(n._op.name)
        if conv is None:
            raise NotImplementedError(
                "ONNX export: no converter for op %r (node %s)"
                % (n._op.name, name))
        ins = []
        for p in getattr(n, "_raw_inputs", n._inputs):
            if isinstance(p, tuple) and p and p[0] == "const":
                ins.append(None if p[1] is None else p[1])
            else:
                ins.append(out_names[(id(p[0]), p[1])])
        conv(ctx, name, ins, n._kwargs)
        out_names[(id(n), 0)] = name

    for pname in pending_params:
        if pname in ctx.skip_params:
            continue
        arr = params[pname]
        ctx.const(pname, arr.asnumpy() if hasattr(arr, "asnumpy") else arr)

    outputs = []
    try:
        kw = {}
        si = list(input_shape or [])
        for n in nodes:
            if n._op is None and n._name not in params and si:
                kw[n._name] = si.pop(0)
        for n in nodes:
            if n._op is None and n._name in params:
                kw[n._name] = tuple(params[n._name].shape)
        _, out_shapes, _ = sym.infer_shape(**kw)
    except Exception:
        out_shapes = None
    for i, (s, oi) in enumerate(sym._outputs_list()):
        oname = out_names[(id(s), oi)]
        shape = tuple(out_shapes[i]) if out_shapes else ()
        outputs.append(P.value_info(oname, shape))

    g = P.graph(ctx.nodes, "mxnet_tpu_graph", inputs, outputs,
                ctx.initializers)
    buf = P.model(g, opset=11)
    with open(onnx_file_path, "wb") as f:
        f.write(buf)
    return onnx_file_path
