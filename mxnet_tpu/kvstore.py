"""KVStore: the gradient-aggregation / parameter-sync surface.

Parity surface: reference ``python/mxnet/kvstore.py`` +
``src/kvstore/`` (N12-N15 in SURVEY §2.1): `KVStore::Create` modes
`local`/`device`/`nccl`/`dist_sync`/`dist_async`/`dist_device_sync`
(`src/kvstore/kvstore.cc:40`), Init/Push/Pull/PushPull/set_updater
(`include/mxnet/kvstore.h:105-438`).

TPU-native design (SURVEY §5.8): there are no server processes and no key
sharding — a single-process store aggregates across local device copies
(role of `CommDevice` `src/kvstore/comm.h:451`), and the distributed mode
``dist_tpu_sync`` [aliases: dist_sync, dist_device_sync, nccl] rides XLA
collectives: `rank`/`num_workers` come from `jax.process_index/count`, and
cross-host reduction happens *inside* the compiled training step (see
mxnet_tpu.parallel) — the eager push/pull path here uses a psum over the
global mesh when multiple processes are attached. `dist_async` is
anti-idiomatic on TPU and raises (SURVEY §2.4).
"""
from __future__ import annotations

import pickle

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import ndarray as _nd
from .ndarray.ndarray import NDArray
from .resilience import chaos as _chaos
from .resilience import retry as _retry

__all__ = ["KVStore", "create"]


def _key_str(key):
    return str(key)


class KVStore:
    """Single-interface store over local devices / TPU mesh."""

    def __init__(self, kind="local", retry_policy=None):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._residuals = {}
        self._is_dist = kind.startswith("dist") or kind == "nccl"
        # transient faults on push/pull (a flaky collective, an injected
        # chaos fault) are absorbed by the env-configured "retry.kvstore"
        # policy (own name: uncontended counters, attributable /metrics
        # rows); pass retry_policy=False to disable
        if retry_policy is None:
            retry_policy = _retry.named_policy("retry.kvstore")
        self._retry = retry_policy or None

    # ---- identity ---------------------------------------------------------
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return jax.process_index() if self._is_dist else 0

    @property
    def num_workers(self):
        return jax.process_count() if self._is_dist else 1

    # ---- init/push/pull ---------------------------------------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            # the store owns its buffer (reference: server/comm buffers are
            # separate allocations) — aliasing the caller's weight would let
            # a donated optimizer update delete the caller's array
            self._store[k] = NDArray(jnp.array(v._data, copy=True),
                                     ctx=v._ctx)
            # re-initializing a key starts a fresh compression history
            for rk in [rk for rk in self._residuals if rk[0] == k]:
                del self._residuals[rk]

    def _reduce(self, values):
        """Sum gradients across device copies (reference CommDevice::Reduce
        `src/kvstore/comm.h:451`). On TPU the copies live on one chip or a
        mesh; the eager sum lowers to XLA adds / ICI transfers."""
        if len(values) == 1:
            out = values[0]._data
        else:
            dev0 = values[0]._data.devices() if hasattr(values[0]._data, "devices") else None
            acc = values[0]._data
            for v in values[1:]:
                vv = v._data
                acc = acc + (jax.device_put(vv, next(iter(dev0)))
                             if dev0 and vv.devices() != values[0]._data.devices()
                             else vv)
            out = acc
        if self._is_dist and jax.process_count() > 1:
            # a peer lost mid-allreduce blocks here forever, not loudly:
            # the elastic collective watchdog turns the wedge into a
            # CollectiveTimeout abort (off unless
            # MXNET_ELASTIC_COLLECTIVE_DEADLINE_MS is set)
            from .resilience.elastic import guard_collective
            out = guard_collective(_cross_process_allreduce, out,
                                   op="kvstore.allreduce")
        return out

    def push(self, key, value, priority=0):
        if self._retry is not None:
            return self._retry.call(self._push_once, key, value, priority)
        return self._push_once(key, value, priority)

    def _push_once(self, key, value, priority=0):
        # chaos point at entry, BEFORE compression/update mutate anything:
        # a retried injected fault can never double-consume error-feedback
        # residuals or double-apply the updater
        _chaos.point("kvstore.push")
        keys, values = _key_value(key, value)
        grouped = {}
        for k, v in zip(keys, values):
            grouped.setdefault(k, []).append(v)
        for k, vals in grouped.items():
            if k not in self._store:
                # check before compression: a failed push must not consume
                # or leak error-feedback residual state
                raise MXNetError("key %s has not been initialized" % k)
            if self._compression_params:
                vals = [NDArray(self._compress(k, i, v._data), ctx=v._ctx)
                        for i, v in enumerate(vals)]
            reduced = self._reduce(vals)
            if self._updater is not None:
                gw = NDArray(reduced)
                self._updater(_key_int(k), gw, self._store[k])
            else:
                # replace, not accumulate (reference kvstore_local.h:
                # `local = merged`); owned copy — with one pushed value
                # _reduce returns the caller's buffer, and a later donated
                # update on the caller's array would delete the stored value
                self._store[k]._data = jnp.array(reduced, copy=True)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if self._retry is not None:
            return self._retry.call(self._pull_once, key, out, priority,
                                    ignore_sparse)
        return self._pull_once(key, out, priority, ignore_sparse)

    def _pull_once(self, key, out=None, priority=0, ignore_sparse=True):
        _chaos.point("kvstore.pull")
        keys, outs = _key_value(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %s has not been initialized" % k)
            src = self._store[k]
            # per-out copy: device_put is zero-copy between CPU devices and
            # onto the same chip, and handing the same buffer to several
            # outs (or leaving an out aliasing the store) breaks buffer
            # donation downstream
            val = jnp.array(src._data, copy=True)
            if o.ctx != src.ctx:
                val = jax.device_put(val, o.ctx.jax_device)
            o._data = val.astype(o._data.dtype) if o._data.dtype != val.dtype else val

    def pushpull(self, key, value, out=None, priority=0):
        """Fused allreduce (reference KVStore::PushPull
        `include/mxnet/kvstore.h:236`). On TPU this is the natural single
        collective; push+pull decomposition is the legacy path."""
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows named by ``row_ids`` into a row_sparse
        output (reference KVStore::PullRowSparse `kvstore_local.h:359`:
        row ids are deduplicated+sorted, values gathered server-side so
        only the touched rows travel)."""
        if row_ids is None or out is None:
            return self.pull(key, out=out, priority=priority)
        import numpy as _onp
        from .ndarray.sparse import RowSparseNDArray
        keys, outs = _key_value(key, out)
        # a list is per-key ONLY when it lines up with the key list and
        # holds array-likes; a plain [0, 2] row-id list for a single key
        # must stay one id-set (it would otherwise zip away rows)
        if isinstance(row_ids, (list, tuple)) and \
                len(row_ids) == len(keys) and \
                all(hasattr(r, "__len__") or hasattr(r, "shape")
                    for r in row_ids):
            rids = list(row_ids)
        else:
            rids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, rids):
            if k not in self._store:
                raise MXNetError("key %s has not been initialized" % k)
            if not isinstance(o, RowSparseNDArray):
                # dense out keeps the full-value pull semantics (reference
                # dense fallback path); only row_sparse outs row-filter
                self.pull(k, out=o, priority=priority)
                continue
            src = self._store[k]
            idx = _onp.unique(_onp.asarray(
                rid.asnumpy() if hasattr(rid, "asnumpy") else rid
            ).astype(_onp.int64).ravel())
            if idx.size and (idx[0] < 0 or idx[-1] >= src.shape[0]):
                # jax gather would CLAMP out-of-range ids — silently wrong
                raise MXNetError(
                    "row_sparse_pull: row id out of range for key %s "
                    "(shape %s, ids [%d, %d])"
                    % (k, src.shape, int(idx[0]), int(idx[-1])))
            out_dtype = o.dtype
            vals = src._data[jnp.asarray(idx)].astype(out_dtype)
            o._values = jnp.asarray(vals)
            o._idx = jnp.asarray(idx)
            o._dense_cache = None
            o._shape_ = tuple(src.shape)
            o._dtype_ = _onp.dtype(out_dtype)

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    # ---- optimizer --------------------------------------------------------
    def set_updater(self, updater):
        """reference `kvstore.py` set_updater — local mode runs the
        optimizer inside the store (update_on_kvstore)."""
        self._updater = updater

    def set_optimizer(self, optimizer):
        from . import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def is_capable(self, capability):
        if capability.lower() == "optimizer":
            return not self._is_dist or True
        return False

    # ---- compression ------------------------------------------------------
    def set_gradient_compression(self, compression_params):
        """reference N15 `src/kvstore/gradient_compression.{h,cc}` (2-bit
        threshold quantization with error feedback on dist push).

        TPU-native: ICI usually makes compression unnecessary (SURVEY
        §2.4), but the mechanism is real here, applied per pushed copy in
        ``push``:

        - ``{'type': '2bit', 'threshold': t}`` — reference semantics:
          each element quantizes to {-t, 0, +t}; the quantization error is
          kept as a per-(key, copy) residual added to the next push.
        - ``{'type': 'int8'}`` — symmetric per-tensor int8 (scale =
          max|x|/127) with the same error feedback; the dequantized int8
          payload is what crosses devices.
        """
        if compression_params is not None:
            ctype = compression_params.get("type")
            if ctype not in ("2bit", "int8", "none", None):
                raise MXNetError("unsupported gradient compression type %r"
                                 % (ctype,))
            if ctype == "2bit":
                t = float(compression_params.get("threshold", 0.5))
                if t <= 0:
                    # reference gradient_compression.cc SetParams rejects
                    # non-positive thresholds too
                    raise MXNetError(
                        "2bit compression threshold must be > 0, got %r"
                        % (t,))
        self._compression_params = compression_params
        self._residuals = {}

    def _compress(self, k, slot, v):
        """Quantize one pushed copy with error feedback; returns the
        dequantized payload (what the wire would carry)."""
        params = self._compression_params
        ctype = params.get("type")
        if ctype in (None, "none"):
            return v
        res = self._residuals.get((k, slot))
        x = v if res is None else v + res
        if ctype == "2bit":
            t = jnp.asarray(float(params.get("threshold", 0.5)), v.dtype)
            deq = jnp.where(x >= t, t, jnp.where(x <= -t, -t,
                                                 jnp.zeros_like(x)))
        else:  # int8
            scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12) / 127.0
            q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
            deq = q.astype(v.dtype) * scale.astype(v.dtype)
        self._residuals[(k, slot)] = x - deq
        return deq

    # ---- distributed control ----------------------------------------------
    def barrier(self):
        if self._is_dist and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            from .resilience.elastic import guard_collective
            # same watchdog as the allreduce: a barrier whose peer died is
            # the canonical silent wedge
            guard_collective(multihost_utils.sync_global_devices,
                             "kvstore_barrier", op="kvstore.barrier")

    def _barrier(self):
        self.barrier()

    def send_command_to_servers(self, head, body):
        pass  # no server processes on TPU (SURVEY §5.8)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    @property
    def num_dead_node(self):
        return 0


_allreduce_cache = {}


def _cross_process_allreduce(x):
    """True allreduce across processes: each process contributes its local
    value on one device of a global 1-D mesh and a jitted `psum` rides the
    interconnect (ICI/DCN on TPU pods, gloo-style on the CPU backend) —
    O(size) per link, unlike allgather-then-sum which moves O(N*size) to
    every host. Replaces the reference PS push/aggregate round
    (`src/kvstore/kvstore_dist_server.h:337`) with one collective."""
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map

    nproc = jax.process_count()
    key = ("mesh", nproc)
    if key not in _allreduce_cache:
        # one device per process so each host contributes exactly one shard
        devs = [[d for d in jax.devices() if d.process_index == p][0]
                for p in range(nproc)]
        _allreduce_cache[key] = Mesh(_np.array(devs), ("p",))
    mesh = _allreduce_cache[key]

    fkey = ("fn", nproc)
    if fkey not in _allreduce_cache:
        def _psum(v):
            return jax.lax.psum(v, "p")
        _allreduce_cache[fkey] = jax.jit(
            shard_map(_psum, mesh=mesh, in_specs=P("p"), out_specs=P()))
    fn = _allreduce_cache[fkey]

    local = _np.asarray(x)[None]  # leading axis = this process's shard
    glob = multihost_utils.host_local_array_to_global_array(local, mesh, P("p"))
    summed = fn(glob)  # (1, *x.shape), replicated
    return jnp.asarray(_np.asarray(
        multihost_utils.global_array_to_host_local_array(summed, mesh, P()))[0])


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _key_value(key, value):
    single = not isinstance(key, (list, tuple))
    if single:
        if isinstance(value, (list, tuple)):
            return [_key_str(key)] * len(value), list(value)
        return [_key_str(key)], [value]
    keys, values = [], []
    for k, v in zip(key, value):
        if isinstance(v, (list, tuple)):
            keys.extend([_key_str(k)] * len(v))
            values.extend(v)
        else:
            keys.append(_key_str(k))
            values.append(v)
    return keys, values


def create(name="local"):
    """Factory (reference `KVStore::Create` `src/kvstore/kvstore.cc:40`)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device"):
        return KVStore(name)
    if name in ("dist_tpu_sync", "dist_sync", "dist_device_sync", "nccl",
                "dist"):
        return KVStore("dist_tpu_sync")
    if name == "dist_async":
        raise MXNetError(
            "dist_async is unsupported on TPU: asynchronous parameter-server "
            "updates are anti-idiomatic for an ICI mesh (SURVEY §2.4); use "
            "dist_tpu_sync")
    raise MXNetError("unknown KVStore type %s" % name)
