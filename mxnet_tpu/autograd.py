"""Autograd: imperative differentiation API.

Parity surface: reference ``python/mxnet/autograd.py`` (record :122,
pause :146, train_mode/predict_mode, backward :246, grad :273, Function
:368) over ``src/imperative/imperative.cc``.

TPU-native: recording builds a tape of pure JAX ops (mxnet_tpu/_tape.py);
``backward`` lowers the whole recorded graph through one ``jax.vjp`` call —
XLA compiles forward+backward together instead of op-at-a-time kernels.
"""
from __future__ import annotations

from . import _tape
from ._tape import is_recording, is_training
from .ndarray.ndarray import NDArray

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "set_recording", "set_training"]


def set_recording(is_rec):
    return _tape.set_recording(is_rec)


def set_training(train):
    return _tape.set_training(train)


class _RecordingStateScope:
    def __init__(self, is_record, train_mode_):
        self._enter_rec = is_record
        self._enter_train = train_mode_
        self._prev_rec = None
        self._prev_train = None

    def __enter__(self):
        if self._enter_rec is not None:
            self._prev_rec = _tape.set_recording(self._enter_rec)
        if self._enter_train is not None:
            self._prev_train = _tape.set_training(self._enter_train)
        return self

    def __exit__(self, *a):
        if self._prev_rec is not None or self._enter_rec is not None:
            _tape.set_recording(self._prev_rec)
        if self._prev_train is not None or self._enter_train is not None:
            _tape.set_training(self._prev_train)


def record(train_mode=True):
    """Scope: ops executed inside are recorded for backward."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """reference Imperative::MarkVariables `src/imperative/imperative.cc:123`."""
    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._ag_node = (_tape.Leaf(v), 0)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and isinstance(head_grads, NDArray):
            head_grads = [head_grads]
    _tape.backward(heads, head_grads, retain_graph=retain_graph,
                   train_mode=train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Functional gradient (reference autograd.grad `python/mxnet/autograd.py:273`).
    With create_graph=True the returned grads are recorded onto the tape so
    higher-order gradients work (replayed through jax.vjp again)."""
    import jax.numpy as jnp
    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    for v in variables:
        if v._ag_node is None:
            raise ValueError("variable passed to grad() must have attach_grad/"
                             "mark_variables called or be used under record()")
    heads_idx = []
    grads_in = []
    for i, h in enumerate(heads):
        if h._ag_node is None:
            raise ValueError("head not recorded")
        heads_idx.append(h._ag_node)
        if head_grads is None or head_grads[i] is None:
            grads_in.append(jnp.ones(h.shape, dtype=h._data.dtype))
        else:
            grads_in.append(head_grads[i]._data)

    var_leaves = [v._ag_node[0] for v in variables]
    order = _tape._toposort([n for n, _ in heads_idx])
    leaves = [l for l in _tape._collect_leaves(order)]
    # ensure requested variables present even if unreached
    leaf_ids = {id(l) for l in leaves}
    import jax
    leaf_vals = [l.handle._data for l in leaves]

    def fn(lv):
        return _tape._replay(order, heads_idx, leaves, lv)

    if create_graph:
        # record the grad computation as a single tape node
        def grad_fn(*args):
            lv = list(args[:len(leaves)])
            gs = list(args[len(leaves):])
            _, vjp_fn = jax.vjp(lambda l: _tape._replay(order, heads_idx, leaves, l), lv)
            (g_out,) = vjp_fn(gs)
            return tuple(g_out)

        parents = [_leaf_parent(l) for l in leaves]
        parents += [_tape.Const(g) for g in grads_in]
        node = _tape.OpNode(grad_fn, parents, len(leaves), {}, "_backward")
        vals = grad_fn(*([lv for lv in leaf_vals] + grads_in))
        out_by_leaf = {id(l): (node, i, v) for i, (l, v) in enumerate(zip(leaves, vals))}
    else:
        _, vjp_fn = jax.vjp(fn, leaf_vals)
        (gvals,) = vjp_fn(grads_in)
        out_by_leaf = {id(l): (None, i, v) for i, (l, v) in enumerate(zip(leaves, gvals))}

    results = []
    for v in variables:
        leaf = v._ag_node[0]
        if id(leaf) in out_by_leaf:
            nd, i, val = out_by_leaf[id(leaf)]
            arr = NDArray(val, ctx=v._ctx)
            if nd is not None and _tape.is_recording():
                arr._ag_node = (nd, i)
            results.append(arr)
        else:
            results.append(NDArray(jnp.zeros(v.shape, v._data.dtype), ctx=v._ctx))
    return results


def _leaf_parent(l):
    return (l, 0)


class Function:
    """Custom differentiable function (reference autograd.Function
    `python/mxnet/autograd.py:368`): user defines forward() and backward().
    Lowered as a jax.custom_vjp around the recorded node."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        import jax
        import jax.numpy as jnp
        self_ref = self

        outs = self.forward(*inputs)
        multi = isinstance(outs, (list, tuple))
        outs_t = tuple(outs) if multi else (outs,)

        if _tape.is_recording():
            def fwd_fn(*vals):
                nds = [NDArray(v) for v in vals]
                with pause():
                    res = self_ref.forward(*nds)
                res = res if isinstance(res, (list, tuple)) else [res]
                return tuple(r._data for r in res)

            @jax.custom_vjp
            def wrapped(*vals):
                return fwd_fn(*vals)

            def wrapped_fwd(*vals):
                return fwd_fn(*vals), vals

            def wrapped_bwd(res_vals, gs):
                g_nds = [NDArray(g) for g in gs]
                with pause():
                    igrads = self_ref.backward(*g_nds)
                igrads = igrads if isinstance(igrads, (list, tuple)) else [igrads]
                return tuple(ig._data for ig in igrads)

            wrapped.defvjp(wrapped_fwd, wrapped_bwd)

            parents = []
            for a in inputs:
                node = a._ag_node
                parents.append(node if node is not None else _tape.Const(a._data))
            node = _tape.OpNode(wrapped, parents, len(outs_t), {},
                                type(self).__name__)
            for i, o in enumerate(outs_t):
                o._ag_node = (node, i)
        return outs
