"""Transformer language model (flagship model for the TPU build).

The reference ships LSTM/attention examples built from ops
(`example/gluon/word_language_model`, `example/nmt`); this provides the
modern equivalent as a first-class Gluon model, designed mesh-first:
parameter names carry `qkv`/`proj`/`ffn_up`/`ffn_down` markers so
tensor-parallel PartitionSpec rules (mxnet_tpu.parallel.shard_params) apply
by regex — the Megatron split: qkv/ffn_up column-sharded on 'tp', proj/
ffn_down row-sharded — and attention routes through the
`_contrib_dot_product_attention` op (swappable for the pallas flash kernel
/ ring attention under sequence parallelism).
"""
from __future__ import annotations

import math

import numpy as np

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer", "TransformerLM",
           "transformer_lm_tiny", "transformer_lm_small", "transformer_lm_base"]


class MultiHeadAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0, causal=True, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, use_bias=False,
                                in_units=units, prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=False,
                                 in_units=units, prefix="proj_")

    def hybrid_forward(self, F, x):
        # x: (B, T, C). q/k/v stay in the natural (B, T, H, D) layout —
        # the head-fused BSHD flash kernel consumes it directly, so no
        # physical transpose brackets the attention (XPlane study: the
        # BHSD shuffles cost ~12% of a BERT-base s128 training span)
        B, T, C = x.shape
        q, k, v = self._split_qkv(x)
        out = F._contrib_dot_product_attention(
            q, k, v, dropout=self._dropout, causal=self._causal,
            layout="BSHD")
        return self.proj(out.reshape((B, T, C)))

    def _split_qkv(self, x):
        B, T, C = x.shape
        H = self._num_heads
        qkv = self.qkv(x)  # (B, T, 3C)
        qkv = qkv.reshape((B, T, 3, H, C // H))
        return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    # ---- incremental decode (KV-cache) path -------------------------------
    def forward_kv(self, x, kv_mask=None):
        """Full-prefix forward that also returns this layer's K/V.

        ``x (B, T, C)``; ``kv_mask (B, T)`` keep-mask for padded prompt
        tails (``None`` = every position valid). Returns
        ``(out (B, T, C), k (B, T, H, D), v (B, T, H, D))`` — the K/V the
        generation prefill copies into its cache arena."""
        from .. import ndarray as nd
        B, T, C = x.shape
        q, k, v = self._split_qkv(x)
        out = nd._contrib_dot_product_attention(
            q, k, v, mask=kv_mask, dropout=self._dropout,
            causal=self._causal, layout="BSHD")
        return self.proj(out.reshape((B, T, C))), k, v

    def step(self, x, k_cache, v_cache, positions):
        """One incremental-decode step against cached K/V.

        ``x (B, 1, C)`` is the new token's hidden state; ``k_cache`` /
        ``v_cache (B, S, H, D)`` hold the first ``positions[b]`` keys and
        values per row. Writes the new K/V at ``positions`` (per-row
        ``dynamic_update_slice``), attends the 1-token query against all
        cached positions ``<= positions[b]``, and returns
        ``(out (B, 1, C), new_k_cache, new_v_cache)``."""
        from .. import ndarray as nd
        B, T, C = x.shape
        q, k, v = self._split_qkv(x)
        k_cache = nd.kv_cache_update(k_cache, k, positions)
        v_cache = nd.kv_cache_update(v_cache, v, positions)
        S = k_cache.shape[1]
        span = nd.arange(0, S, dtype="int32").reshape((1, S))
        kv_mask = span < (positions.reshape((B, 1)) + 1)
        # single-token query: validity lives entirely in kv_mask, so the
        # causal flag is off (q's position IS the last unmasked key)
        out = nd._contrib_dot_product_attention(
            q, k_cache, v_cache, mask=kv_mask, dropout=0.0, causal=False,
            layout="BSHD")
        return self.proj(out.reshape((B, 1, C))), k_cache, v_cache

    def step_chunk(self, x, k_cache, v_cache, start):
        """A multi-token incremental step: append a whole *chunk* of new
        hidden states against cached K/V.

        ``x (B, C, units)`` is a chunk of C consecutive positions starting
        at absolute position ``start[b]`` per row; ``k_cache`` /
        ``v_cache (B, S, H, D)`` hold the first ``start[b]`` committed
        keys/values. Writes the chunk's K/V at ``start`` (per-row
        ``dynamic_update_slice``) and attends each chunk query at absolute
        position ``start[b] + i`` to every cached position ``<= start[b]
        + i`` — causal *within* the chunk, full over the prefix. This is
        the one program shape behind chunked prefill, prefix-cache suffix
        fill, and the speculative verify step: ``step`` is the ``C == 1``
        special case, a full prefill is the ``start == 0`` special case.
        Chunk rows past the caller's valid count produce garbage outputs
        AND garbage cache writes — both unreachable, because committed
        lengths gate every later attention mask and the next chunk's
        write overlays the pad tail before reading it."""
        from .. import ndarray as nd
        B, C, _ = x.shape
        q, k, v = self._split_qkv(x)
        k_cache = nd.kv_cache_update(k_cache, k, start)
        v_cache = nd.kv_cache_update(v_cache, v, start)
        S = k_cache.shape[1]
        span = nd.arange(0, S, dtype="int32").reshape((1, 1, S))
        qpos = start.reshape((B, 1, 1)) + \
            nd.arange(0, C, dtype="int32").reshape((1, C, 1))
        kv_mask = (span < qpos + 1).reshape((B, 1, C, S))
        out = nd._contrib_dot_product_attention(
            q, k_cache, v_cache, mask=kv_mask, dropout=0.0, causal=False,
            layout="BSHD")
        return self.proj(out.reshape((B, C, self._units))), k_cache, v_cache


class TransformerEncoderLayer(HybridBlock):
    """Pre-norm block (attention + MLP)."""

    def __init__(self, units, num_heads, hidden_size, dropout=0.0,
                 causal=True, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads, dropout, causal)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.ffn_up = nn.Dense(hidden_size, flatten=False,
                                   in_units=units, prefix="ffn_up_")
            self.ffn_down = nn.Dense(units, flatten=False,
                                     in_units=hidden_size,
                                     prefix="ffn_down_")
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x):
        x = x + self.dropout(self.attn(self.ln1(x)))
        h = F.LeakyReLU(self.ffn_up(self.ln2(x)), act_type="gelu")
        x = x + self.dropout(self.ffn_down(h))
        return x

    def _ffn(self, x):
        from .. import ndarray as nd
        h = nd.LeakyReLU(self.ffn_up(self.ln2(x)), act_type="gelu")
        return x + self.dropout(self.ffn_down(h))

    def forward_kv(self, x, kv_mask=None):
        """Full-prefix forward returning ``(out, k, v)`` (see
        :meth:`MultiHeadAttention.forward_kv`)."""
        a, k, v = self.attn.forward_kv(self.ln1(x), kv_mask)
        return self._ffn(x + self.dropout(a)), k, v

    def step(self, x, k_cache, v_cache, positions):
        """Incremental-decode step (see :meth:`MultiHeadAttention.step`)."""
        a, k_cache, v_cache = self.attn.step(self.ln1(x), k_cache, v_cache,
                                             positions)
        return self._ffn(x + self.dropout(a)), k_cache, v_cache

    def step_chunk(self, x, k_cache, v_cache, start):
        """Chunk-append step (see :meth:`MultiHeadAttention.step_chunk`)."""
        a, k_cache, v_cache = self.attn.step_chunk(self.ln1(x), k_cache,
                                                   v_cache, start)
        return self._ffn(x + self.dropout(a)), k_cache, v_cache


class TransformerLM(HybridBlock):
    """Decoder-only LM: embed → N blocks → norm → logits."""

    def __init__(self, vocab_size, units=256, num_layers=4, num_heads=8,
                 hidden_size=None, max_len=2048, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        hidden_size = hidden_size or 4 * units
        self._units = units
        self._max_len = max_len
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.pos_embed = nn.Embedding(max_len, units, prefix="pos_")
            self.blocks = nn.HybridSequential(prefix="blocks_")
            with self.blocks.name_scope():
                for _ in range(num_layers):
                    self.blocks.add(TransformerEncoderLayer(
                        units, num_heads, hidden_size, dropout))
            self.ln_f = nn.LayerNorm(in_channels=units)
            self.head = nn.Dense(vocab_size, flatten=False, use_bias=False,
                                 in_units=units, prefix="head_")

    def hybrid_forward(self, F, tokens):
        # tokens: (B, T) int
        B, T = tokens.shape
        from .. import ndarray as nd
        pos = nd.arange(0, T, dtype="int32")
        x = self.embed(tokens) + self.pos_embed(pos)
        x = self.blocks(x)
        x = self.ln_f(x)
        return self.head(x)

    # ---- incremental decode (KV-cache) path -------------------------------
    @property
    def num_heads(self):
        return next(iter(self.blocks)).attn._num_heads

    @property
    def head_dim(self):
        return self._units // self.num_heads

    @property
    def num_layers(self):
        return len(self.blocks)

    @property
    def units(self):
        return self._units

    @property
    def max_len(self):
        return self._max_len

    def init_cache(self, batch_size, max_len=None, dtype="float32"):
        """Zeroed per-layer KV caches: ``[(k, v), ...]`` with each buffer
        ``(batch_size, max_len, heads, head_dim)``."""
        from .. import ndarray as nd
        S = int(max_len or self._max_len)
        shape = (int(batch_size), S, self.num_heads, self.head_dim)
        return [(nd.zeros(shape, dtype=dtype), nd.zeros(shape, dtype=dtype))
                for _ in range(self.num_layers)]

    def prefill(self, tokens, lengths=None):
        """Fill a KV cache from a (padded) prompt in ONE forward pass.

        ``tokens (B, T)`` int; ``lengths (B,)`` int32 valid lengths
        (``None`` = all ``T``). Returns ``(logits, cache)`` where
        ``logits (B, vocab)`` belongs to each row's LAST VALID position
        and ``cache`` is ``[(k, v), ...]`` with ``(B, T, H, D)`` buffers —
        positions past ``lengths[b]`` contain garbage that downstream
        attention must keep masked (``TransformerLM.step`` does)."""
        from .. import ndarray as nd
        B, T = tokens.shape
        pos = nd.arange(0, T, dtype="int32")
        x = self.embed(tokens) + self.pos_embed(pos)
        if lengths is None:
            lengths = nd.full((B,), T, dtype="int32")
        kv_mask = pos.reshape((1, T)) < lengths.reshape((B, 1))
        cache = []
        for blk in self.blocks:
            x, k, v = blk.forward_kv(x, kv_mask)
            cache.append((k, v))
        x = self.ln_f(x)
        # gather each row's last valid hidden state (one-hot contraction:
        # stays one fused program under jit, no host round-trip)
        last = nd.one_hot(lengths - 1, depth=T)              # (B, T)
        h_last = nd.sum(x * last.reshape((B, T, 1)), axis=1)  # (B, C)
        return self.head(h_last), cache

    def prefill_chunk(self, tokens, cache, start):
        """Append a chunk of ``C`` tokens per row at per-row offsets.

        ``tokens (B, C)`` int — consecutive prompt/draft tokens whose
        first element sits at absolute position ``start[b]`` (int32
        ``(B,)``); ``cache`` as returned by :meth:`init_cache` /
        :meth:`prefill`, holding ``start[b]`` committed positions per
        row. Returns ``(logits (B, C, vocab), new_cache)`` where
        ``logits[b, i]`` is the next-token distribution after consuming
        ``tokens[b, :i+1]`` — exactly what the speculative verify step
        scores and what chunked prefill samples its first token from
        (row ``valid - 1`` of the final chunk). Purely functional like
        :meth:`step`; pad rows write garbage K/V past the caller's valid
        count, unreachable through committed lengths (see
        ``MultiHeadAttention.step_chunk``)."""
        from .. import ndarray as nd
        B, C = tokens.shape
        pos = start.reshape((B, 1)) + \
            nd.arange(0, C, dtype="int32").reshape((1, C))
        # clamp for the position-embedding gather only: pad-tail positions
        # of the final chunk can run past max_len; their rows are garbage
        # by contract either way
        pos = nd.minimum(pos, self._max_len - 1)
        x = self.embed(tokens) + self.pos_embed(pos)
        new_cache = []
        for (k_c, v_c), blk in zip(cache, self.blocks):
            x, k_c, v_c = blk.step_chunk(x, k_c, v_c, start)
            new_cache.append((k_c, v_c))
        x = self.ln_f(x)
        return self.head(x), new_cache

    def step(self, tokens, cache, lengths):
        """One fused decode step for a whole batch of sequences.

        ``tokens (B, 1)`` int — the token to append per row; ``cache`` as
        returned by :meth:`init_cache`/:meth:`prefill`; ``lengths (B,)``
        int32 — how many positions are already cached per row (== the
        position the new token is written at). Returns
        ``(logits (B, vocab), new_cache)``. Purely functional: the caller
        owns cache replacement and length bookkeeping."""
        B = tokens.shape[0]
        x = self.embed(tokens) + self.pos_embed(lengths.reshape((B, 1)))
        new_cache = []
        for (k_c, v_c), blk in zip(cache, self.blocks):
            x, k_c, v_c = blk.step(x, k_c, v_c, lengths)
            new_cache.append((k_c, v_c))
        x = self.ln_f(x)
        return self.head(x.reshape((B, self._units))), new_cache


def tp_rules(spec_cls=None):
    """Megatron-style tensor-parallel rules for TransformerLM params."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"qkv_weight$", P("tp", None)),       # column parallel (out, in)
        (r"ffn_up_weight$", P("tp", None)),
        (r"proj_weight$", P(None, "tp")),      # row parallel
        (r"ffn_down_weight$", P(None, "tp")),
        (r"embed_weight$", P(None, "tp")),
        (r"head_weight$", P("tp", None)),
    ]


def transformer_lm_tiny(vocab_size=1024, **kwargs):
    return TransformerLM(vocab_size, units=64, num_layers=2, num_heads=4,
                         max_len=256, **kwargs)


def transformer_lm_small(vocab_size=32000, **kwargs):
    return TransformerLM(vocab_size, units=512, num_layers=8, num_heads=8,
                         **kwargs)


def transformer_lm_base(vocab_size=32000, **kwargs):
    """BERT-base scale (~110M) decoder."""
    return TransformerLM(vocab_size, units=768, num_layers=12, num_heads=12,
                         **kwargs)
