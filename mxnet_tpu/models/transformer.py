"""Transformer language model (flagship model for the TPU build).

The reference ships LSTM/attention examples built from ops
(`example/gluon/word_language_model`, `example/nmt`); this provides the
modern equivalent as a first-class Gluon model, designed mesh-first:
parameter names carry `qkv`/`proj`/`ffn_up`/`ffn_down` markers so
tensor-parallel PartitionSpec rules (mxnet_tpu.parallel.shard_params) apply
by regex — the Megatron split: qkv/ffn_up column-sharded on 'tp', proj/
ffn_down row-sharded — and attention routes through the
`_contrib_dot_product_attention` op (swappable for the pallas flash kernel
/ ring attention under sequence parallelism).
"""
from __future__ import annotations

import math

import numpy as np

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer", "TransformerLM",
           "transformer_lm_tiny", "transformer_lm_small", "transformer_lm_base"]


class MultiHeadAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0, causal=True, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, use_bias=False,
                                in_units=units, prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=False,
                                 in_units=units, prefix="proj_")

    def hybrid_forward(self, F, x):
        # x: (B, T, C). q/k/v stay in the natural (B, T, H, D) layout —
        # the head-fused BSHD flash kernel consumes it directly, so no
        # physical transpose brackets the attention (XPlane study: the
        # BHSD shuffles cost ~12% of a BERT-base s128 training span)
        B, T, C = x.shape
        H = self._num_heads
        qkv = self.qkv(x)  # (B, T, 3C)
        qkv = qkv.reshape((B, T, 3, H, C // H))
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        out = F._contrib_dot_product_attention(
            q, k, v, dropout=self._dropout, causal=self._causal,
            layout="BSHD")
        return self.proj(out.reshape((B, T, C)))


class TransformerEncoderLayer(HybridBlock):
    """Pre-norm block (attention + MLP)."""

    def __init__(self, units, num_heads, hidden_size, dropout=0.0,
                 causal=True, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads, dropout, causal)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.ffn_up = nn.Dense(hidden_size, flatten=False,
                                   in_units=units, prefix="ffn_up_")
            self.ffn_down = nn.Dense(units, flatten=False,
                                     in_units=hidden_size,
                                     prefix="ffn_down_")
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x):
        x = x + self.dropout(self.attn(self.ln1(x)))
        h = F.LeakyReLU(self.ffn_up(self.ln2(x)), act_type="gelu")
        x = x + self.dropout(self.ffn_down(h))
        return x


class TransformerLM(HybridBlock):
    """Decoder-only LM: embed → N blocks → norm → logits."""

    def __init__(self, vocab_size, units=256, num_layers=4, num_heads=8,
                 hidden_size=None, max_len=2048, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        hidden_size = hidden_size or 4 * units
        self._units = units
        self._max_len = max_len
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.pos_embed = nn.Embedding(max_len, units, prefix="pos_")
            self.blocks = nn.HybridSequential(prefix="blocks_")
            with self.blocks.name_scope():
                for _ in range(num_layers):
                    self.blocks.add(TransformerEncoderLayer(
                        units, num_heads, hidden_size, dropout))
            self.ln_f = nn.LayerNorm(in_channels=units)
            self.head = nn.Dense(vocab_size, flatten=False, use_bias=False,
                                 in_units=units, prefix="head_")

    def hybrid_forward(self, F, tokens):
        # tokens: (B, T) int
        B, T = tokens.shape
        from .. import ndarray as nd
        pos = nd.arange(0, T, dtype="int32")
        x = self.embed(tokens) + self.pos_embed(pos)
        x = self.blocks(x)
        x = self.ln_f(x)
        return self.head(x)


def tp_rules(spec_cls=None):
    """Megatron-style tensor-parallel rules for TransformerLM params."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"qkv_weight$", P("tp", None)),       # column parallel (out, in)
        (r"ffn_up_weight$", P("tp", None)),
        (r"proj_weight$", P(None, "tp")),      # row parallel
        (r"ffn_down_weight$", P(None, "tp")),
        (r"embed_weight$", P(None, "tp")),
        (r"head_weight$", P("tp", None)),
    ]


def transformer_lm_tiny(vocab_size=1024, **kwargs):
    return TransformerLM(vocab_size, units=64, num_layers=2, num_heads=4,
                         max_len=256, **kwargs)


def transformer_lm_small(vocab_size=32000, **kwargs):
    return TransformerLM(vocab_size, units=512, num_layers=8, num_heads=8,
                         **kwargs)


def transformer_lm_base(vocab_size=32000, **kwargs):
    """BERT-base scale (~110M) decoder."""
    return TransformerLM(vocab_size, units=768, num_layers=12, num_heads=12,
                         **kwargs)
