"""BERT for masked-LM + next-sentence pretraining (BASELINE.md reference
config "BERT-base pretraining"; the reference ecosystem ships BERT via
GluonNLP on the same Gluon substrate).

Mesh-first like models/transformer.py: parameter names carry qkv/proj/
ffn_up/ffn_down markers so the Megatron tensor-parallel rules
(`mxnet_tpu.parallel` + `models.transformer.tp_rules`) apply unchanged;
attention routes through `_contrib_dot_product_attention` (flash kernel /
ring attention capable). Padding is handled with a boolean keep-mask
broadcast to (B, 1, 1, T) — XLA fuses it into the softmax."""
from __future__ import annotations

import math

import numpy as np

from ..gluon.block import HybridBlock
from ..gluon import nn
from .transformer import MultiHeadAttention, tp_rules  # noqa: F401

__all__ = ["BERTModel", "BERTEncoder", "bert_tiny", "bert_base",
           "BERTPretrainingLoss"]


def _gather_positions(F, x, positions):
    """Gather (B, M, ...) rows of ``x`` (B, T, ...) at integer ``positions``
    (B, M) — shared by the gather-first decode and the loss fallback."""
    B, M = positions.shape
    rows = F.arange(0, B).reshape((B, 1))
    rows = F.broadcast_mul(rows, F.ones_like(positions))
    idx = F.stack(rows.reshape((-1,)), positions.reshape((-1,)), axis=0)
    return F.gather_nd(x, idx)                         # (B*M, ...)


class _MaskedAttention(MultiHeadAttention):
    """MultiHeadAttention with a padding keep-mask (bidirectional)."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(units, num_heads, dropout=dropout, causal=False,
                         **kwargs)

    def hybrid_forward(self, F, x, mask=None):
        # natural (B, T, H, D) layout end to end (see MultiHeadAttention)
        B, T, C = x.shape
        H = self._num_heads
        qkv = self.qkv(x)
        qkv = qkv.reshape((B, T, 3, H, C // H))
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        out = F._contrib_dot_product_attention(
            q, k, v, mask=mask, dropout=self._dropout, causal=False,
            layout="BSHD")
        return self.proj(out.reshape((B, T, C)))


class _BERTLayer(HybridBlock):
    """Post-norm encoder block (BERT convention: residual -> LayerNorm)."""

    def __init__(self, units, num_heads, hidden_size, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attn = _MaskedAttention(units, num_heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn_up = nn.Dense(hidden_size, flatten=False,
                                   in_units=units, prefix="ffn_up_")
            self.ffn_down = nn.Dense(units, flatten=False,
                                     in_units=hidden_size,
                                     prefix="ffn_down_")
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None):
        x = self.ln1(x + self.dropout(self.attn(x, mask)))
        h = F.LeakyReLU(self.ffn_up(x), act_type="gelu")
        x = self.ln2(x + self.dropout(self.ffn_down(h)))
        return x


class BERTEncoder(HybridBlock):
    """Token + segment + learned-position embeddings -> N encoder blocks."""

    def __init__(self, vocab_size, units, num_layers, num_heads,
                 hidden_size, max_length=512, num_segments=2, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self._max_length = max_length
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.segment_embed = nn.Embedding(num_segments, units,
                                              prefix="segment_embed_")
            self.pos_embed = nn.Embedding(max_length, units,
                                          prefix="pos_embed_")
            self.ln = nn.LayerNorm(in_channels=units)
            self.dropout = nn.Dropout(dropout)
            self.layers = []
            for i in range(num_layers):
                layer = _BERTLayer(units, num_heads, hidden_size, dropout,
                                   prefix="layer%d_" % i)
                self.layers.append(layer)
                self.register_child(layer)

    def hybrid_forward(self, F, tokens, segments, valid_len=None):
        B, T = tokens.shape
        pos = F.arange(0, T).reshape((1, T))
        x = self.word_embed(tokens) + self.segment_embed(segments) \
            + self.pos_embed(pos)
        x = self.dropout(self.ln(x))
        mask = None
        if valid_len is not None:
            # keep-mask (B, 1, 1, T): every query may attend to keys < len
            ar = F.arange(0, T).reshape((1, 1, 1, T))
            mask = F.broadcast_lesser(
                ar, valid_len.reshape((B, 1, 1, 1)))
        for layer in self.layers:
            x = layer(x, mask)
        return x


class BERTModel(HybridBlock):
    """Encoder + pooler + MLM decoder + NSP classifier (pretraining heads).

    Forward returns ``(sequence_output, pooled, mlm_logits, nsp_logits)``.
    """

    def __init__(self, vocab_size=30522, units=768, num_layers=12,
                 num_heads=12, hidden_size=3072, max_length=512,
                 num_segments=2, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.vocab_size = vocab_size
        with self.name_scope():
            self.encoder = BERTEncoder(vocab_size, units, num_layers,
                                       num_heads, hidden_size, max_length,
                                       num_segments, dropout,
                                       prefix="encoder_")
            self.pooler = nn.Dense(units, flatten=False, in_units=units,
                                   prefix="pooler_")
            self.mlm_transform = nn.Dense(units, flatten=False,
                                          in_units=units,
                                          prefix="mlm_transform_")
            self.mlm_ln = nn.LayerNorm(in_channels=units)
            self.mlm_decoder = nn.Dense(vocab_size, flatten=False,
                                        in_units=units,
                                        prefix="mlm_decoder_")
            self.nsp = nn.Dense(2, flatten=False, in_units=units,
                                prefix="nsp_")

    def hybrid_forward(self, F, tokens, segments, valid_len=None,
                       masked_positions=None):
        seq = self.encoder(tokens, segments, valid_len)
        cls = F.slice_axis(seq, axis=1, begin=0, end=1).reshape(
            (seq.shape[0], -1))
        pooled = F.tanh(self.pooler(cls))
        if masked_positions is not None:
            # gather-FIRST (reference GluonNLP BERTModel._decode: the MLM
            # transform + vocab decoder run only on the M masked slots, not
            # all T positions — at s128/M20 that is 6.4x less vocab-head
            # work; the round-5 XPlane study measured full-seq decoding at
            # ~18% of the training step)
            B, M = masked_positions.shape
            picked = _gather_positions(F, seq, masked_positions).reshape(
                (B, M, -1))
            h = F.LeakyReLU(self.mlm_transform(picked), act_type="gelu")
            mlm_logits = self.mlm_decoder(self.mlm_ln(h))  # (B, M, V)
        else:
            h = F.LeakyReLU(self.mlm_transform(seq), act_type="gelu")
            mlm_logits = self.mlm_decoder(self.mlm_ln(h))  # (B, T, V)
        nsp_logits = self.nsp(pooled)
        return seq, pooled, mlm_logits, nsp_logits


class BERTPretrainingLoss(HybridBlock):
    """Masked-LM + next-sentence loss. ``mlm_positions`` selects the masked
    slots (B, M); ``mlm_weights`` zeroes padding in M.

    ``picked=True`` declares that ``mlm_logits`` is already (B, M, V) from
    the model's gather-first decode (``masked_positions`` passed to
    ``BERTModel``) — explicit, because shape inference alone cannot
    distinguish full-sequence logits when T == M."""

    def __init__(self, picked=False, **kwargs):
        super().__init__(**kwargs)
        self._picked = picked

    def hybrid_forward(self, F, mlm_logits, nsp_logits, mlm_labels,
                       mlm_positions, mlm_weights, nsp_labels):
        B, M = mlm_positions.shape
        V = mlm_logits.shape[-1]
        if self._picked:
            assert mlm_logits.shape[1] == M, \
                "picked=True expects (B, M, V) logits"
            picked = mlm_logits.reshape((B * M, V))
        else:
            picked = _gather_positions(F, mlm_logits, mlm_positions)
        logp = F.log_softmax(picked, axis=-1)
        ll = F.pick(logp, mlm_labels.reshape((-1,)), axis=-1)
        w = mlm_weights.reshape((-1,))
        mlm_loss = -F.sum(ll * w) / (F.sum(w) + 1e-6)
        nsp_logp = F.log_softmax(nsp_logits, axis=-1)
        nsp_loss = -F.mean(F.pick(nsp_logp, nsp_labels, axis=-1))
        return mlm_loss + nsp_loss


def bert_tiny(vocab_size=1000, max_length=128, **kwargs):
    """2-layer/128-unit config for tests and the multichip dryrun."""
    return BERTModel(vocab_size=vocab_size, units=128, num_layers=2,
                     num_heads=2, hidden_size=512, max_length=max_length,
                     dropout=0.0, **kwargs)


def bert_base(vocab_size=30522, **kwargs):
    """BERT-base: 12 layers x 768 units x 12 heads (the BASELINE.md
    pretraining reference config)."""
    return BERTModel(vocab_size=vocab_size, units=768, num_layers=12,
                     num_heads=12, hidden_size=3072, **kwargs)
