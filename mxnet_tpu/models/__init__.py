"""First-class model families beyond the vision zoo."""
from .transformer import (TransformerLM, MultiHeadAttention,
                          TransformerEncoderLayer, transformer_lm_tiny,
                          transformer_lm_small, transformer_lm_base, tp_rules)
from .moe_transformer import MoETransformerLM, moe_lm_tiny
from .lstm_lm import RNNModel
