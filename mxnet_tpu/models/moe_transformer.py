"""Stage-stacked Mixture-of-Experts transformer LM — the planner's
flagship workload (ROADMAP item 2: a model that does not fit one chip).

Design is mesh-first for the :mod:`~mxnet_tpu.parallel.planner` naming
convention: every per-layer parameter is ONE tensor with a leading
``n_stages`` axis (``stack_*`` -> ``PartitionSpec('pp')``), and the
expert FFN weights carry ``(n_stages, n_experts, ...)`` leading axes
(``stack_expert_*`` -> ``PartitionSpec('pp', 'ep')``) so a
:class:`~mxnet_tpu.parallel.planner.ShardingPlan` places the whole model
by regex — dp x pp x ep on one mesh, XLA's SPMD partitioner inserting
the all_to_alls/collective-permutes the placement implies. The MoE FFN
is :func:`~mxnet_tpu.parallel.moe.moe_ffn` (Switch top-1 routing, static
capacity, over-capacity tokens dropped) on the full token pool; its
load-balancing aux loss is returned by :meth:`MoETransformerLM.aux_loss`
after a forward for callers that want to add it.

Unlike :class:`~mxnet_tpu.models.transformer.TransformerLM` (generation-
serving oriented, per-layer sub-blocks), this model trades block
modularity for stacked parameters: a python loop over stages indexes
each stage's slab out of the pp-sharded stack, which keeps one parameter
per logical tensor — exactly what elastic reshard-on-restore needs
(checkpoints re-place the SAME full tensors under a different plan,
bitwise).
"""
from __future__ import annotations

import numpy as np

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["MoETransformerLM", "moe_lm_tiny"]


def _ln(x, gamma, beta, eps=1e-5):
    import jax.numpy as jnp
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * gamma + beta


class MoETransformerLM(HybridBlock):
    """Decoder-only LM: embed -> n_stages x [attn + MoE FFN] -> logits.

    All per-stage parameters are stacked on a leading ``n_stages`` axis
    (planner convention); attention is causal, dropout-free (the
    elastic-resume contract wants bitwise-deterministic replay)."""

    def __init__(self, vocab_size=64, units=32, num_heads=2, num_layers=2,
                 hidden_size=None, n_experts=4, max_len=64,
                 capacity_factor=2.0, **kwargs):
        super().__init__(**kwargs)
        hidden_size = hidden_size or 2 * units
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._num_layers = num_layers
        self._n_experts = n_experts
        self._capacity_factor = capacity_factor
        self._max_len = max_len
        self._aux = None
        L, D, H, E = num_layers, units, hidden_size, n_experts
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.pos_embed = nn.Embedding(max_len, units, prefix="pos_")
            self.head = nn.Dense(vocab_size, flatten=False, use_bias=False,
                                 in_units=units, prefix="head_")
            get = self.params.get
            self.stack_ln1_gamma = get("stack_ln1_gamma", shape=(L, D),
                                       init="ones")
            self.stack_ln1_beta = get("stack_ln1_beta", shape=(L, D),
                                      init="zeros")
            self.stack_ln2_gamma = get("stack_ln2_gamma", shape=(L, D),
                                       init="ones")
            self.stack_ln2_beta = get("stack_ln2_beta", shape=(L, D),
                                      init="zeros")
            self.stack_qkv_weight = get("stack_qkv_weight",
                                        shape=(L, D, 3 * D))
            self.stack_proj_weight = get("stack_proj_weight",
                                         shape=(L, D, D))
            self.stack_gate_weight = get("stack_gate_weight",
                                         shape=(L, D, E))
            self.stack_expert_w1 = get("stack_expert_w1",
                                       shape=(L, E, D, H))
            self.stack_expert_w2 = get("stack_expert_w2",
                                       shape=(L, E, H, D))

    @property
    def n_experts(self):
        return self._n_experts

    @property
    def num_layers(self):
        return self._num_layers

    def profile(self, batch, seq, **kwargs):
        """The planner's :class:`~mxnet_tpu.parallel.planner.ModelProfile`
        for this model at one batch geometry."""
        from ..parallel.planner import ModelProfile
        return ModelProfile.from_block(self, batch, seq=seq,
                                       d_model=self._units, **kwargs)

    def aux_loss(self):
        """Switch load-balancing aux loss summed over stages from the
        most recent forward (traced value; add it to the objective if
        desired — the default objective leaves it out so routing drift
        never breaks bitwise replay comparisons across PRs)."""
        return self._aux

    def _attn(self, x, qkv_w, proj_w):
        import jax.numpy as jnp
        B, T, D = x.shape
        Hn = self._num_heads
        hd = D // Hn
        qkv = x @ qkv_w                                   # (B, T, 3D)
        qkv = qkv.reshape(B, T, 3, Hn, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = q.transpose(0, 2, 1, 3)                       # (B, H, T, hd)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hd)
        causal = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(causal[None, None], s, -jnp.inf)
        p = jax_softmax(s)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
        return out @ proj_w

    def hybrid_forward(self, F, tokens, stack_ln1_gamma, stack_ln1_beta,
                       stack_ln2_gamma, stack_ln2_beta, stack_qkv_weight,
                       stack_proj_weight, stack_gate_weight,
                       stack_expert_w1, stack_expert_w2):
        from .. import ndarray as nd
        from ..ndarray.ndarray import NDArray
        from ..parallel.moe import moe_ffn

        B, T = tokens.shape
        pos = nd.arange(0, T, dtype="int32")
        x = (self.embed(tokens) + self.pos_embed(pos))._data
        g1, b1 = stack_ln1_gamma._data, stack_ln1_beta._data
        g2, b2 = stack_ln2_gamma._data, stack_ln2_beta._data
        qkv_w, proj_w = stack_qkv_weight._data, stack_proj_weight._data
        gate_w = stack_gate_weight._data
        w1, w2 = stack_expert_w1._data, stack_expert_w2._data
        aux_total = 0.0
        for i in range(self._num_layers):
            x = x + self._attn(_ln(x, g1[i], b1[i]), qkv_w[i], proj_w[i])
            y, aux = moe_ffn(_ln(x, g2[i], b2[i]), gate_w[i], w1[i], w2[i],
                             capacity_factor=self._capacity_factor)
            x = x + y
            aux_total = aux_total + aux
        self._aux = aux_total
        return self.head(NDArray(x))


    # ---- incremental decode (KV-cache) path -------------------------------
    # Same contract as TransformerLM (what DecodeEngine compiles its
    # fused fixed-signature programs against): properties + init_cache /
    # prefill / prefill_chunk / step. The MoE FFN stays moe_ffn — under
    # jit with the expert stacks committed onto an 'ep' mesh axis, the
    # SPMD partitioner shards the expert einsums, so the SAME contract
    # serves expert-parallel with zero decode-path changes.

    @property
    def num_heads(self):
        return self._num_heads

    @property
    def head_dim(self):
        return self._units // self._num_heads

    @property
    def units(self):
        return self._units

    @property
    def max_len(self):
        return self._max_len

    def init_cache(self, batch_size, max_len=None, dtype="float32"):
        """Zeroed per-layer KV caches: ``[(k, v), ...]`` with each buffer
        ``(batch_size, max_len, heads, head_dim)``."""
        from .. import ndarray as nd
        S = int(max_len or self._max_len)
        shape = (int(batch_size), S, self.num_heads, self.head_dim)
        return [(nd.zeros(shape, dtype=dtype), nd.zeros(shape, dtype=dtype))
                for _ in range(self._num_layers)]

    def _slabs(self):
        """The stacked parameter tensors as raw jax values."""
        return (self.stack_ln1_gamma.data()._data,
                self.stack_ln1_beta.data()._data,
                self.stack_ln2_gamma.data()._data,
                self.stack_ln2_beta.data()._data,
                self.stack_qkv_weight.data()._data,
                self.stack_proj_weight.data()._data,
                self.stack_gate_weight.data()._data,
                self.stack_expert_w1.data()._data,
                self.stack_expert_w2.data()._data)

    def _split_qkv(self, xv, qkv_w):
        """(B, T, D) hidden -> q/k/v in BSHD layout, one slab's weights."""
        B, T, D = xv.shape
        Hn = self._num_heads
        hd = D // Hn
        qkv = (xv @ qkv_w).reshape(B, T, 3, Hn, hd)
        return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    def _moe(self, xv, gate_w, w1, w2):
        from ..parallel.moe import moe_ffn
        y, _aux = moe_ffn(xv, gate_w, w1, w2,
                          capacity_factor=self._capacity_factor)
        return y

    def prefill(self, tokens, lengths=None):
        """Fill a KV cache from a (padded) prompt in ONE forward pass.
        Same contract as :meth:`TransformerLM.prefill`: returns
        ``(logits (B, vocab) at each row's last valid position,
        cache [(k, v), ...])``."""
        from .. import ndarray as nd
        from ..ndarray.ndarray import NDArray
        B, T = tokens.shape
        pos = nd.arange(0, T, dtype="int32")
        x = self.embed(tokens) + self.pos_embed(pos)
        if lengths is None:
            lengths = nd.full((B,), T, dtype="int32")
        kv_mask = pos.reshape((1, T)) < lengths.reshape((B, 1))
        g1, b1, g2, b2, qkv_w, proj_w, gate_w, w1, w2 = self._slabs()
        xv = x._data
        cache = []
        for i in range(self._num_layers):
            h = _ln(xv, g1[i], b1[i])
            q, k, v = self._split_qkv(h, qkv_w[i])
            out = nd._contrib_dot_product_attention(
                NDArray(q), NDArray(k), NDArray(v), mask=kv_mask,
                causal=True, layout="BSHD")
            xv = xv + out._data.reshape(B, T, self._units) @ proj_w[i]
            xv = xv + self._moe(_ln(xv, g2[i], b2[i]), gate_w[i],
                                w1[i], w2[i])
            cache.append((NDArray(k), NDArray(v)))
        last = nd.one_hot(lengths - 1, depth=T)              # (B, T)
        h_last = nd.sum(NDArray(xv) * last.reshape((B, T, 1)), axis=1)
        return self.head(h_last), cache

    def _incremental(self, tokens, cache, start, chunk):
        """Shared body of :meth:`step` (chunk=False, C==1) and
        :meth:`prefill_chunk` (chunk=True): append C tokens per row at
        per-row offsets ``start`` against cached K/V, purely
        functional. Returns ``(hidden (B, C, D), new_cache)``."""
        from .. import ndarray as nd
        from ..ndarray.ndarray import NDArray
        B, C = tokens.shape
        if chunk:
            pos = start.reshape((B, 1)) + \
                nd.arange(0, C, dtype="int32").reshape((1, C))
            # clamp for the position-embedding gather only (pad tails of
            # the final chunk may run past max_len; garbage by contract)
            pos = nd.minimum(pos, self._max_len - 1)
        else:
            pos = start.reshape((B, 1))
        x = self.embed(tokens) + self.pos_embed(pos)
        g1, b1, g2, b2, qkv_w, proj_w, gate_w, w1, w2 = self._slabs()
        xv = x._data
        new_cache = []
        for i, (k_c, v_c) in enumerate(cache):
            h = _ln(xv, g1[i], b1[i])
            q, k, v = self._split_qkv(h, qkv_w[i])
            k_c = nd.kv_cache_update(k_c, NDArray(k), start)
            v_c = nd.kv_cache_update(v_c, NDArray(v), start)
            S = k_c.shape[1]
            if chunk:
                span = nd.arange(0, S, dtype="int32").reshape((1, 1, S))
                qpos = start.reshape((B, 1, 1)) + \
                    nd.arange(0, C, dtype="int32").reshape((1, C, 1))
                kv_mask = (span < qpos + 1).reshape((B, 1, C, S))
            else:
                span = nd.arange(0, S, dtype="int32").reshape((1, S))
                kv_mask = span < (start.reshape((B, 1)) + 1)
            out = nd._contrib_dot_product_attention(
                NDArray(q), k_c, v_c, mask=kv_mask, dropout=0.0,
                causal=False, layout="BSHD")
            xv = xv + out._data.reshape(B, C, self._units) @ proj_w[i]
            xv = xv + self._moe(_ln(xv, g2[i], b2[i]), gate_w[i],
                                w1[i], w2[i])
            new_cache.append((k_c, v_c))
        return xv, new_cache

    def prefill_chunk(self, tokens, cache, start):
        """Append a chunk of ``C`` tokens per row at per-row offsets;
        same contract as :meth:`TransformerLM.prefill_chunk`. Returns
        ``(logits (B, C, vocab), new_cache)``."""
        from ..ndarray.ndarray import NDArray
        xv, new_cache = self._incremental(tokens, cache, start, chunk=True)
        return self.head(NDArray(xv)), new_cache

    def step(self, tokens, cache, lengths):
        """One fused decode step; same contract as
        :meth:`TransformerLM.step`. Returns ``(logits (B, vocab),
        new_cache)``."""
        from ..ndarray.ndarray import NDArray
        B = tokens.shape[0]
        xv, new_cache = self._incremental(tokens, cache, lengths,
                                          chunk=False)
        return self.head(NDArray(xv.reshape(B, self._units))), new_cache


def jax_softmax(s):
    import jax
    return jax.nn.softmax(s, axis=-1)


def moe_lm_tiny(vocab_size=64, n_experts=4, num_layers=2, **kwargs):
    """The CPU-oracle test/bench configuration: 2 stages x 4 experts —
    factorable as dp·pp2·ep{1,2,4} on an 8-device pool."""
    return MoETransformerLM(vocab_size, units=32, num_heads=2,
                            num_layers=num_layers, n_experts=n_experts,
                            max_len=64, **kwargs)
