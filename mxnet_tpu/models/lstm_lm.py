"""LSTM word language model (reference
`example/gluon/word_language_model/model.py` RNNModel)."""
from __future__ import annotations

from ..gluon.block import HybridBlock
from ..gluon import nn, rnn

__all__ = ["RNNModel"]


class RNNModel(HybridBlock):
    """Embedding → (LSTM/GRU/RNN) → Dense decoder, optional tied weights."""

    def __init__(self, mode="lstm", vocab_size=10000, num_embed=200,
                 num_hidden=200, num_layers=2, dropout=0.5, tie_weights=False,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(
                vocab_size, num_embed,
                weight_initializer=None)
            if mode == "lstm":
                self.rnn = rnn.LSTM(num_hidden, num_layers, dropout=dropout,
                                    input_size=num_embed)
            elif mode == "gru":
                self.rnn = rnn.GRU(num_hidden, num_layers, dropout=dropout,
                                   input_size=num_embed)
            else:
                self.rnn = rnn.RNN(num_hidden, num_layers, dropout=dropout,
                                   input_size=num_embed,
                                   activation="relu" if mode == "rnn_relu"
                                   else "tanh")
            if tie_weights:
                assert num_embed == num_hidden
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        params=self.encoder.params)
            else:
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        in_units=num_hidden)
            self.num_hidden = num_hidden

    def hybrid_forward(self, F, inputs, hidden=None):
        # inputs: (T, B) int tokens
        emb = self.drop(self.encoder(inputs))
        if hidden is None:
            output = self.rnn(emb)
            output = self.drop(output)
            return self.decoder(output)
        output, hidden = self.rnn(emb, hidden)
        output = self.drop(output)
        decoded = self.decoder(output)
        return decoded, hidden

    def begin_state(self, *args, **kwargs):
        return self.rnn.begin_state(*args, **kwargs)
