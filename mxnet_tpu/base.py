"""Base utilities: dtypes, errors, registry plumbing.

TPU-native re-design of the roles of ``python/mxnet/base.py`` (reference
`python/mxnet/base.py`) — but with no ctypes FFI for the compute path: the
"runtime" is JAX/XLA, so the bridge layer the reference needs (check_call,
handle types) collapses to plain Python. The native C++ runtime pieces this
framework does have (engine, recordio) expose their own ctypes bridge in
``mxnet_tpu._ffi``.
"""
from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError", "string_types", "numeric_types", "integer_types",
    "dtype_np", "dtype_name", "_as_list",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# Canonical dtype table. MXNet uses an int code enum (reference
# `python/mxnet/ndarray/ndarray.py:54` _DTYPE_NP_TO_MX); on TPU the canonical
# low-precision type is bfloat16 rather than float16, but both are supported.
_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bf16": "bfloat16",
}


def dtype_np(dtype):
    """Normalize a user dtype spec to a numpy dtype (incl. bfloat16)."""
    if dtype is None:
        return _np.dtype("float32")
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
        if dtype == "bfloat16":
            import ml_dtypes
            return _np.dtype(ml_dtypes.bfloat16)
    if not isinstance(dtype, type) and hasattr(dtype, "dtype"):
        # array-like instance (NDArray, jax array): take its dtype; plain
        # scalar types like np.uint8 carry a class-level descriptor and
        # must go straight to np.dtype
        dtype = dtype.dtype
    return _np.dtype(dtype)


def dtype_name(dtype) -> str:
    return dtype_np(dtype).name


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
