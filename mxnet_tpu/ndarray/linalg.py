"""mx.nd.linalg namespace (reference `python/mxnet/ndarray/linalg.py` over
src/operator/linalg ops)."""
from ..ops.registry import get_op as _get_op


def __getattr__(name):
    op = _get_op("linalg_" + name) or _get_op(name)
    if op is None:
        raise AttributeError("no linalg operator %r" % name)
    return op
