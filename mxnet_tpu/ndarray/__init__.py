"""``mx.nd`` namespace: NDArray + the full generated op namespace.

The reference generates this module's functions from the C op registry at
import (reference `python/mxnet/ndarray/register.py`); we do the same from
the Python-side registry — one source of truth for eager, symbolic, and
numpy frontends."""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange, eye,
                      concat, stack, save, load, waitall, from_numpy,
                      from_dlpack, to_dlpack_for_read, to_dlpack_for_write)
from . import sparse
from .. import ops as _ops
from ..ops.registry import get_op as _get_op, list_ops as _list_ops
from .. import random as _random_mod

# contrib namespace (control flow + contrib ops)
from . import contrib  # noqa: F401
from . import linalg   # noqa: F401
from . import random   # noqa: F401

_ops.populate_namespace(globals())


def __getattr__(name):
    op = _get_op(name)
    if op is None:
        raise AttributeError("module 'mxnet_tpu.ndarray' has no attribute %r" % name)
    return op


def imresize(*a, **k):
    from ..image import imresize as _f
    return _f(*a, **k)


def Custom(*args, **kwargs):
    """Invoke a registered Python CustomOp (reference
    `python/mxnet/ndarray/ndarray.py` Custom → custom-inl.h). Accepts
    mxnet-style keyword tensor inputs (``Custom(data=x, op_type='...')``)."""
    from ..operator import normalize_custom_args
    tensors, call_kwargs = normalize_custom_args(args, kwargs)
    call_kwargs.pop("name", None)
    return _get_op("Custom")(*tensors, **call_kwargs)
