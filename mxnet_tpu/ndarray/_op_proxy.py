"""Dynamic op lookup for NDArray methods (PEP 562 module __getattr__).

Plays the role of the generated per-op Python functions the reference builds
at import time (reference `python/mxnet/ndarray/register.py:270`)."""
from ..ops.registry import get_op


def __getattr__(name):
    op = get_op(name)
    if op is None:
        raise AttributeError("no operator %r registered" % name)
    return op
