"""NDArray: a mutable handle over an immutable ``jax.Array``.

Role parity: reference ``include/mxnet/ndarray.h:82`` (NDArray with Chunk =
Storage handle + engine var) and ``python/mxnet/ndarray/ndarray.py``.

TPU-native design: the reference needs a Chunk/engine-var pair because eager
GPU kernels require host-side dependency ordering and manual memory pools.
On TPU, a ``jax.Array`` already *is* an asynchronously-produced, refcounted
device buffer managed by PJRT — so NDArray collapses to a thin mutable cell:

  - mutation (``x[:]=``, ``+=``, ``out=``) rebinds ``_data`` to a new
    functional value — the moral equivalent of the reference's var version
    bump (`include/mxnet/engine.h:57`);
  - ``wait_to_read`` = ``block_until_ready`` (reference
    `include/mxnet/ndarray.h:368` WaitToRead → Engine::WaitForVar);
  - cross-device copy = ``jax.device_put`` (reference
    `src/ndarray/ndarray.cc:1142` CopyFromToImpl);
  - the handle can transparently hold a jax tracer, which is what makes the
    whole eager API traceable under jit (CachedOp) with zero extra code.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import dtype_np, numeric_types, integer_types
from ..context import Context, current_context
from .. import _tape

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "eye", "concat", "stack", "save", "load", "waitall",
           "from_numpy", "from_dlpack", "to_dlpack_for_read"]


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


class NDArray:
    """Multi-dimensional array on a device context."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_ag_node", "_stype",
                 "__weakref__")

    def __init__(self, data, ctx=None, dtype=None, stype="default"):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array) and not _is_tracer(data):
            data = _np.asarray(data, dtype=dtype_np(dtype) if dtype else None)
            dev = (ctx or current_context()).jax_device
            data = jax.device_put(data, dev)
        elif dtype is not None and data.dtype != dtype_np(dtype):
            data = data.astype(dtype_np(dtype))
        self._data = data
        self._ctx = ctx
        self._grad = None
        self._grad_req = "write"
        self._ag_node = None
        self._stype = stype

    # ---- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(_np.prod(self.shape)) if self.shape else 1

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def stype(self):
        return self._stype

    @property
    def ctx(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        if _is_tracer(self._data):
            return current_context()
        dev = self._data.devices() if hasattr(self._data, "devices") else None
        if dev:
            d = next(iter(dev))
            if d.platform == "cpu":
                return Context("cpu", d.id)
            return Context("tpu", 0)
        return current_context()

    context = ctx

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return self.transpose()

    # ---- host interop -----------------------------------------------------
    def asnumpy(self) -> _np.ndarray:
        """Blocking copy to host (reference NDArray::SyncCopyToCPU)."""
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def wait_to_read(self):
        if not _is_tracer(self._data):
            jax.block_until_ready(self._data)

    wait_to_write = wait_to_read

    # ---- device movement --------------------------------------------------
    def as_in_context(self, ctx) -> "NDArray":
        if ctx == self.ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copyto(self, other):
        from ..ops import registry as _r
        if isinstance(other, Context):
            dev = other.jax_device
            new = NDArray(jax.device_put(self._data, dev), ctx=other)
            return new
        if isinstance(other, NDArray):
            val = self._data
            if other.ctx != self.ctx and not _is_tracer(val):
                val = jax.device_put(val, other.ctx.jax_device)
            other._data = val.astype(other.dtype) if other.dtype != self.dtype else val
            if not other._is_leaf:
                other._ag_node = self._ag_node
            return other
        raise TypeError("copyto expects NDArray or Context")

    def copy(self):
        return NDArray(self._data, ctx=self._ctx)

    def astype(self, dtype, copy=True):
        nd = dtype_np(dtype)
        if not copy and nd == self.dtype:
            return self
        from . import _op_proxy
        return _op_proxy.cast(self, dtype=nd)

    def tostype(self, stype):
        """Sparse storage conversion — API parity; dense fallback on TPU
        (reference cast_storage `src/operator/tensor/cast_storage.cc`)."""
        from .sparse import _to_stype
        return _to_stype(self, stype)

    # ---- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Mark as differentiable leaf (reference
        `python/mxnet/ndarray/ndarray.py` attach_grad →
        Imperative::MarkVariables `src/imperative/imperative.cc:123`)."""
        self._grad = zeros(self.shape, dtype=self.dtype, ctx=self._ctx)
        self._grad_req = grad_req
        self._ag_node = (_tape.Leaf(self), 0)
        return self

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _tape.backward([self], [out_grad] if out_grad is not None else None,
                       retain_graph=retain_graph, train_mode=train_mode)

    # ---- mutation ---------------------------------------------------------
    @property
    def _is_leaf(self):
        """True when this handle is a marked autograd variable (attach_grad).
        Mutation must NOT unmark it: the Leaf node reads the handle's current
        value at backward time — matching MXNet, where a variable stays a
        variable across in-place optimizer updates (engine var version bumps,
        `include/mxnet/engine.h:57`)."""
        node = self._ag_node
        return (node is not None and isinstance(node[0], _tape.Leaf)
                and node[0].handle is self)

    def _set_data(self, val):
        self._data = val
        if not self._is_leaf:
            self._ag_node = None

    def __setitem__(self, key, value):
        from . import _op_proxy
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, numeric_types):
            v = value
        else:
            v = jnp.asarray(_np.asarray(value))
        if key is None or key == slice(None) or key is Ellipsis:
            if isinstance(v, (int, float)):
                self._set_data(jnp.full(self.shape, v, dtype=self.dtype))
            else:
                v = jnp.asarray(v, dtype=self.dtype)
                self._set_data(jnp.broadcast_to(v, self.shape))
            return
        key = _canonical_index(key)
        self._set_data(self._data.at[key].set(v))

    def __getitem__(self, key):
        from . import _op_proxy
        if isinstance(key, NDArray):
            key = key._data
        key = _canonical_index(key)
        return _op_proxy._index(self, key=key)

    # ---- operators --------------------------------------------------------
    def _binop(self, other, name, reverse=False):
        from . import _op_proxy
        fn = getattr(_op_proxy, name)
        if isinstance(other, NDArray):
            return fn(other, self) if reverse else fn(self, other)
        if isinstance(other, numeric_types):
            return fn(other, self) if reverse else fn(self, other)
        other = array(other, ctx=self._ctx)
        return fn(other, self) if reverse else fn(self, other)

    def __add__(self, o):
        return self._binop(o, "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "subtract")

    def __rsub__(self, o):
        return self._binop(o, "subtract", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "multiply")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "divide")

    def __rtruediv__(self, o):
        return self._binop(o, "divide", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "mod")

    def __rmod__(self, o):
        return self._binop(o, "mod", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "power")

    def __rpow__(self, o):
        return self._binop(o, "power", reverse=True)

    def __matmul__(self, o):
        return self._binop(o, "matmul")

    def __neg__(self):
        return self._binop(-1, "multiply")

    def __abs__(self):
        from . import _op_proxy
        return _op_proxy.abs(self)

    def __eq__(self, o):
        return self._binop(o, "equal")

    def __ne__(self, o):
        return self._binop(o, "not_equal")

    def __lt__(self, o):
        return self._binop(o, "lesser")

    def __le__(self, o):
        return self._binop(o, "lesser_equal")

    def __gt__(self, o):
        return self._binop(o, "greater")

    def __ge__(self, o):
        return self._binop(o, "greater_equal")

    def __hash__(self):
        return id(self)

    # in-place: rebind _data (engine-var version bump equivalent)
    def _inplace(self, other, name):
        res = self._binop(other, name)
        self._data = res._data
        if not self._is_leaf:
            self._ag_node = res._ag_node
        return self

    def __iadd__(self, o):
        return self._inplace(o, "add")

    def __isub__(self, o):
        return self._inplace(o, "subtract")

    def __imul__(self, o):
        return self._inplace(o, "multiply")

    def __itruediv__(self, o):
        return self._inplace(o, "divide")

    # ---- shape ops (delegate to op namespace) -----------------------------
    def reshape(self, *shape, **kwargs):
        from . import _op_proxy
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return _op_proxy.reshape(self, shape=shape)

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, *axes):
        from . import _op_proxy
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return _op_proxy.transpose(self, axes=axes if axes else None)

    def swapaxes(self, a1, a2):
        from . import _op_proxy
        return _op_proxy.swapaxes(self, dim1=a1, dim2=a2)

    def expand_dims(self, axis):
        from . import _op_proxy
        return _op_proxy.expand_dims(self, axis=axis)

    def squeeze(self, axis=None):
        from . import _op_proxy
        return _op_proxy.squeeze(self, axis=axis)

    def flatten(self):
        from . import _op_proxy
        return _op_proxy.Flatten(self)

    def broadcast_to(self, shape):
        from . import _op_proxy
        return _op_proxy.broadcast_to(self, shape=tuple(shape))

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def slice_axis(self, axis, begin, end):
        from . import _op_proxy
        return _op_proxy.slice_axis(self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        from . import _op_proxy
        return _op_proxy.take(self, indices, axis=axis, mode=mode)

    def tile(self, reps):
        from . import _op_proxy
        return _op_proxy.tile(self, reps=reps)

    def repeat(self, repeats, axis=None):
        from . import _op_proxy
        return _op_proxy.repeat(self, repeats=repeats, axis=axis)

    def pick(self, index, axis=-1, keepdims=False):
        from . import _op_proxy
        return _op_proxy.pick(self, index, axis=axis, keepdims=keepdims)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        from . import _op_proxy
        return _op_proxy.one_hot(self, depth=depth, on_value=on_value,
                                 off_value=off_value)

    # ---- reductions -------------------------------------------------------
    def _reduce(self, name, axis=None, keepdims=False):
        from . import _op_proxy
        return getattr(_op_proxy, name)(self, axis=axis, keepdims=keepdims)

    def sum(self, axis=None, keepdims=False, **kw):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return self._reduce("mean", axis, keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        return self._reduce("min", axis, keepdims)

    def prod(self, axis=None, keepdims=False, **kw):
        return self._reduce("prod", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        from . import _op_proxy
        return _op_proxy.norm(self, ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        from . import _op_proxy
        return _op_proxy.argmax(self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        from . import _op_proxy
        return _op_proxy.argmin(self, axis=axis, keepdims=keepdims)

    def clip(self, a_min, a_max):
        from . import _op_proxy
        return _op_proxy.clip(self, a_min=a_min, a_max=a_max)

    def abs(self):
        from . import _op_proxy
        return _op_proxy.abs(self)

    def sqrt(self):
        from . import _op_proxy
        return _op_proxy.sqrt(self)

    def square(self):
        from . import _op_proxy
        return _op_proxy.square(self)

    def exp(self):
        from . import _op_proxy
        return _op_proxy.exp(self)

    def log(self):
        from . import _op_proxy
        return _op_proxy.log(self)

    def relu(self):
        from . import _op_proxy
        return _op_proxy.relu(self)

    def sigmoid(self):
        from . import _op_proxy
        return _op_proxy.sigmoid(self)

    def tanh(self):
        from . import _op_proxy
        return _op_proxy.tanh(self)

    def softmax(self, axis=-1):
        from . import _op_proxy
        return _op_proxy.softmax(self, axis=axis)

    def zeros_like(self):
        return zeros(self.shape, dtype=self.dtype, ctx=self._ctx)

    def ones_like(self):
        return ones(self.shape, dtype=self.dtype, ctx=self._ctx)

    def asnumpy_or_tracer(self):
        return self._data

    def as_np_ndarray(self):
        from ..numpy import ndarray as np_nd
        out = np_nd(self._data, ctx=self._ctx)
        out._ag_node = self._ag_node
        return out

    def as_nd_ndarray(self):
        return self

    def __repr__(self):
        if _is_tracer(self._data):
            return "\n<NDArray traced %s @%s>" % (self.shape, "trace")
        return "\n%s\n<NDArray %s @%s>" % (
            _np.asarray(self._data), "x".join(map(str, self.shape)), self.ctx)

    # ---- numpy protocol ---------------------------------------------------
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


def _canonical_index(key):
    """Convert NDArray-containing index tuples into jax-compatible keys."""
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(k._data if isinstance(k, NDArray) else k for k in key)
    return key


# ---- creation -------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        out = NDArray(source_array._data, ctx=ctx)
        if dtype is not None:
            out = out.astype(dtype)
        return out
    arr = _np.asarray(source_array, dtype=dtype_np(dtype) if dtype else None)
    if arr.dtype == _np.float64 and dtype is None:
        arr = arr.astype(_np.float32)
    return NDArray(arr, ctx=ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    dev = (ctx or current_context()).jax_device
    with jax.default_device(dev):
        v = jnp.zeros(shape, dtype=dtype_np(dtype))
    return NDArray(v, ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    dev = (ctx or current_context()).jax_device
    with jax.default_device(dev):
        v = jnp.ones(shape, dtype=dtype_np(dtype))
    return NDArray(v, ctx=ctx)


def full(shape, val, ctx=None, dtype=None):
    if isinstance(shape, int):
        shape = (shape,)
    dev = (ctx or current_context()).jax_device
    with jax.default_device(dev):
        v = jnp.full(shape, val, dtype=dtype_np(dtype))
    return NDArray(v, ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    v = jnp.arange(start, stop, step, dtype=dtype_np(dtype))
    if repeat > 1:
        v = jnp.repeat(v, repeat)
    return NDArray(v, ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None):
    v = jnp.eye(N, M if M else N, k=k, dtype=dtype_np(dtype))
    return NDArray(v, ctx=ctx)


def concat(*arrays, dim=1):
    from . import _op_proxy
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return _op_proxy.concat(*arrays, dim=dim)


def stack(*arrays, axis=0):
    from . import _op_proxy
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return _op_proxy.stack(*arrays, axis=axis)


def from_numpy(a, zero_copy=False):
    return array(a)


def from_dlpack(cap):
    return NDArray(jnp.from_dlpack(cap))


def to_dlpack_for_read(arr):
    return arr._data.__dlpack__()


to_dlpack_for_write = to_dlpack_for_read


def waitall():
    """Parity with mx.nd.waitall (Engine::WaitForAll)."""
    (jax.device_put(0.0) + 0).block_until_ready()


# ---- serialization (reference NDArray::Save/Load, mx.nd.save/load) --------

def save(fname, data):
    """Save list or dict of NDArrays in the reference's binary list container
    (reference `src/ndarray/ndarray.cc:1826` NDArray::Save) — files written
    here load in the reference and vice versa. See `serialization.py`."""
    from . import serialization
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        serialization.save_ndarrays(fname, list(data))
    elif isinstance(data, dict):
        keys = list(data.keys())
        serialization.save_ndarrays(fname, [data[k] for k in keys], keys)
    else:
        raise TypeError("save expects NDArray, list, or dict")


def load(fname):
    """Load NDArrays saved by `save` or by the reference (binary container);
    .npz files from older checkpoints of this framework still load."""
    import numpy as np
    import os
    from . import serialization
    path = fname if os.path.exists(fname) else fname + ".npz"
    if serialization.is_mxnet_binary(path):
        arrays, names = serialization.load_ndarrays(path)
        if names:
            return {k: array(a, dtype=a.dtype) for k, a in zip(names, arrays)}
        return [array(a, dtype=a.dtype) for a in arrays]
    with np.load(path, allow_pickle=False) as z:
        keys = list(z.keys())
        if "__mx_list__" in keys:
            n = int(z["__mx_list__"])
            arrs = [z["arr_%d" % i] for i in range(n)]
            return [array(a, dtype=a.dtype) for a in arrs]
        out = {}
        for k in keys:
            a = z[k]
            out[k] = array(a, dtype=a.dtype)
        return out
