"""mx.nd.random namespace (reference `python/mxnet/ndarray/random.py`)."""
from ..random import (uniform, normal, randn, randint, gamma, exponential,  # noqa: F401
                      poisson, negative_binomial, generalized_negative_binomial,
                      multinomial, shuffle, bernoulli, seed)
