"""mx.nd.contrib namespace (reference `python/mxnet/ndarray/contrib.py`)."""
from ..ops.contrib_ops import foreach, while_loop, cond  # noqa: F401
from ..contrib.graph import (edge_id, getnnz, dgl_adjacency,  # noqa: F401
                             dgl_subgraph,
                             dgl_csr_neighbor_uniform_sample,
                             dgl_csr_neighbor_non_uniform_sample,
                             dgl_graph_compact)
from ..ops.registry import get_op as _get_op


def __getattr__(name):
    op = _get_op("_contrib_" + name) or _get_op(name)
    if op is None:
        raise AttributeError("no contrib operator %r" % name)
    return op
