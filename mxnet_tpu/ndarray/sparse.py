"""Sparse NDArray API: row_sparse + CSR.

Parity surface: reference ``python/mxnet/ndarray/sparse.py`` and the
storage-type machinery (`include/mxnet/ndarray.h:61-66` kDefaultStorage/
kRowSparseStorage/kCSRStorage; cast_storage
`src/operator/tensor/cast_storage.cc`).

TPU-native design: XLA has no native sparse layouts, so sparse arrays are
API-complete views that keep (indices, data) host/device-side and densify on
compute — the documented dense-fallback strategy (SURVEY §5.9). Row-sparse
gradient *semantics* (the reason MXNet has row_sparse: embedding grads) are
preserved where they matter: optimizers take a `lazy_update` path keyed on
rows, and kvstore row_sparse_pull is supported.
"""
from __future__ import annotations

import numpy as _np
import jax.numpy as jnp

from .ndarray import NDArray, array, zeros as _dense_zeros

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix", "row_sparse_array",
           "zeros", "empty", "array"]


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """Dense-backed row_sparse view: tracks .indices/.data accessors."""
    __slots__ = ("_indices",)

    def __init__(self, data, indices=None, ctx=None, dtype=None):
        super().__init__(data, ctx=ctx, dtype=dtype, stype="row_sparse")
        if indices is None:
            dense = _np.asarray(self._data)
            nz = _np.where(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
            indices = nz
        self._indices = jnp.asarray(_np.asarray(indices, dtype=_np.int64))

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def data(self):
        return NDArray(jnp.take(self._data, self._indices.astype(jnp.int32), axis=0))

    def tostype(self, stype):
        return _to_stype(self, stype)


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ("_indptr_", "_indices_")

    def __init__(self, data, indptr=None, indices=None, ctx=None, dtype=None):
        super().__init__(data, ctx=ctx, dtype=dtype, stype="csr")
        if indptr is None or indices is None:
            dense = _np.asarray(self._data)
            indptr = [0]
            idx = []
            for row in dense:
                nz = _np.nonzero(row)[0]
                idx.extend(nz.tolist())
                indptr.append(len(idx))
            indptr, indices = _np.array(indptr), _np.array(idx)
        self._indptr_ = jnp.asarray(_np.asarray(indptr, dtype=_np.int64))
        self._indices_ = jnp.asarray(_np.asarray(indices, dtype=_np.int64))

    @property
    def indptr(self):
        return NDArray(self._indptr_)

    @property
    def indices(self):
        return NDArray(self._indices_)

    @property
    def data(self):
        dense = _np.asarray(self._data)
        vals = dense[dense != 0] if dense.ndim == 2 else dense
        return NDArray(jnp.asarray(vals))

    def tostype(self, stype):
        return _to_stype(self, stype)


def _to_stype(arr, stype):
    if stype == "default":
        return NDArray(arr._data, ctx=arr._ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(arr._data, ctx=arr._ctx)
    if stype == "csr":
        if arr.ndim != 2:
            raise ValueError("csr requires 2D")
        return CSRNDArray(arr._data, ctx=arr._ctx)
    raise ValueError("unknown stype %r" % stype)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = _np.asarray(data)
        indices = _np.asarray(indices, dtype=_np.int64)
        indptr = _np.asarray(indptr, dtype=_np.int64)
        n_rows = len(indptr) - 1
        n_cols = shape[1] if shape else int(indices.max()) + 1
        dense = _np.zeros((n_rows, n_cols), dtype=data.dtype)
        for r in range(n_rows):
            for j in range(indptr[r], indptr[r + 1]):
                dense[r, indices[j]] = data[j]
        return CSRNDArray(dense, indptr=indptr, indices=indices, ctx=ctx, dtype=dtype)
    return CSRNDArray(_np.asarray(arg1), ctx=ctx, dtype=dtype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = _np.asarray(data)
        indices = _np.asarray(indices, dtype=_np.int64)
        n_rows = shape[0] if shape else int(indices.max()) + 1
        dense = _np.zeros((n_rows,) + data.shape[1:], dtype=data.dtype)
        dense[indices] = data
        return RowSparseNDArray(dense, indices=indices, ctx=ctx, dtype=dtype)
    return RowSparseNDArray(_np.asarray(arg1), ctx=ctx, dtype=dtype)


def zeros(stype, shape, ctx=None, dtype=None):
    d = _dense_zeros(shape, ctx=ctx, dtype=dtype)
    return _to_stype(d, stype)


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)
