"""Sparse NDArray API: row_sparse + CSR with real compressed storage.

Parity surface: reference ``python/mxnet/ndarray/sparse.py`` and the
storage-type machinery (`include/mxnet/ndarray.h:61-66` kDefaultStorage/
kRowSparseStorage/kCSRStorage; cast_storage
`src/operator/tensor/cast_storage-inl.h`; sparse dot
`src/operator/tensor/dot-inl.h`).

TPU-native design: the *compressed payload is the authoritative storage* —
``RowSparseNDArray`` holds (values[nnz_rows, ...], indices[nnz_rows]) and
``CSRNDArray`` holds (data[nnz], indices[nnz], indptr[rows+1]) as device
arrays. XLA has no native sparse layouts, so dense views are materialized
lazily (one vectorized scatter) and cached; sparse-aware compute paths
(``sparse.dot`` via gather + segment_sum, ``sparse.retain``, row-sparse
optimizer updates) never densify. This mirrors the reference's split between
storage (Chunk aux_data) and FComputeEx sparse kernels.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from .ndarray import NDArray, array as _dense_array, zeros as _dense_zeros

__all__ = ["BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "csr_matrix", "row_sparse_array", "zeros", "empty", "array",
           "dot", "retain", "add"]


def _compress_rows(dense):
    """dense (host or device) -> (values, indices) dropping all-zero rows."""
    d = _np.asarray(dense)
    nz = _np.where(d.reshape(d.shape[0], -1).any(axis=1))[0]
    return jnp.asarray(d[nz]), jnp.asarray(nz.astype(_np.int64))


def _compress_csr(dense):
    d = _np.asarray(dense)
    if d.ndim != 2:
        raise ValueError("csr requires 2D")
    rows, cols = _np.nonzero(d)
    data = d[rows, cols]
    indptr = _np.zeros(d.shape[0] + 1, dtype=_np.int64)
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr)
    return (jnp.asarray(data), jnp.asarray(cols.astype(_np.int64)),
            jnp.asarray(indptr))


class BaseSparseNDArray(NDArray):
    """Common lazy-densify machinery. Subclasses keep compressed payloads in
    their own slots; ``_data`` (the dense jax.Array every inherited NDArray
    method uses) is a property that scatters on first touch and caches."""
    __slots__ = ("_dense_cache", "_shape_", "_dtype_")

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._densify()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        # mutation rebind (x[:] = ..., +=): dense value becomes truth;
        # recompress lazily on next payload access
        self._dense_cache = value
        self._shape_ = tuple(value.shape)
        self._dtype_ = value.dtype
        self._invalidate_payload()

    @property
    def shape(self):
        return self._shape_

    @property
    def dtype(self):
        return _np.dtype(self._dtype_)

    def tostype(self, stype):
        return _to_stype(self, stype)

    def asnumpy(self):
        return _np.asarray(self._data)


class RowSparseNDArray(BaseSparseNDArray):
    """values[nnz_rows, cols...] + indices[nnz_rows] — reference
    `python/mxnet/ndarray/sparse.py` RowSparseNDArray (aux kIdx)."""
    __slots__ = ("_values", "_idx")

    def __init__(self, values, indices, shape, ctx=None, dtype=None):
        v = jnp.asarray(values)
        if dtype is not None:
            from ..base import dtype_np
            v = v.astype(dtype_np(dtype))
        # bypass NDArray.__init__'s dense handling: set handle slots directly
        self._values = v
        self._idx = jnp.asarray(indices).astype(jnp.int64)
        self._shape_ = tuple(shape)
        self._dtype_ = v.dtype
        self._dense_cache = None
        self._ctx = ctx
        self._grad = None
        self._grad_req = "write"
        self._ag_node = None
        self._stype = "row_sparse"

    def _densify(self):
        out = jnp.zeros(self._shape_, dtype=self._dtype_)
        if self._values.shape[0] == 0:
            return out
        return out.at[self._idx].set(
            self._values.astype(self._dtype_))

    def _invalidate_payload(self):
        self._values = None
        self._idx = None

    def _payload(self):
        if self._values is None:
            self._values, self._idx = _compress_rows(self._dense_cache)
        return self._values, self._idx

    @property
    def indices(self):
        return NDArray(self._payload()[1])

    @property
    def data(self):
        return NDArray(self._payload()[0])

    def copy(self):
        v, i = self._payload()
        return RowSparseNDArray(v, i, self._shape_, ctx=self._ctx)

    def __repr__(self):
        return ("<RowSparseNDArray %s @%s>" %
                (self._shape_, self.ctx))


class CSRNDArray(BaseSparseNDArray):
    """data[nnz] + indices[nnz] + indptr[rows+1] — reference CSRNDArray
    (aux kIndPtr/kIdx)."""
    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr")

    def __init__(self, data, indices, indptr, shape, ctx=None, dtype=None):
        v = jnp.asarray(data)
        if dtype is not None:
            from ..base import dtype_np
            v = v.astype(dtype_np(dtype))
        self._csr_data = v
        self._csr_indices = jnp.asarray(indices).astype(jnp.int64)
        self._csr_indptr = jnp.asarray(indptr).astype(jnp.int64)
        self._shape_ = tuple(shape)
        self._dtype_ = v.dtype
        self._dense_cache = None
        self._ctx = ctx
        self._grad = None
        self._grad_req = "write"
        self._ag_node = None
        self._stype = "csr"

    def _row_ids(self):
        counts = _np.diff(_np.asarray(self._csr_indptr))
        return jnp.asarray(
            _np.repeat(_np.arange(self._shape_[0]), counts).astype(_np.int64))

    def _densify(self):
        out = jnp.zeros(self._shape_, dtype=self._dtype_)
        if self._csr_data.shape[0] == 0:
            return out
        return out.at[self._row_ids(), self._csr_indices].set(
            self._csr_data.astype(self._dtype_))

    def _invalidate_payload(self):
        self._csr_data = None
        self._csr_indices = None
        self._csr_indptr = None

    def _payload(self):
        if self._csr_data is None:
            (self._csr_data, self._csr_indices,
             self._csr_indptr) = _compress_csr(self._dense_cache)
        return self._csr_data, self._csr_indices, self._csr_indptr

    @property
    def data(self):
        return NDArray(self._payload()[0])

    @property
    def indices(self):
        return NDArray(self._payload()[1])

    @property
    def indptr(self):
        return NDArray(self._payload()[2])

    def copy(self):
        d, i, p = self._payload()
        return CSRNDArray(d, i, p, self._shape_, ctx=self._ctx)

    def __repr__(self):
        return "<CSRNDArray %s @%s>" % (self._shape_, self.ctx)


def _to_stype(arr, stype):
    if stype == arr.stype:
        # cast_storage contract is a copy (reference cast_storage-inl.h):
        # mutating the result must not touch the source handle
        if isinstance(arr, BaseSparseNDArray):
            return arr.copy()
        return NDArray(arr._data, ctx=arr._ctx)
    if stype == "default":
        return NDArray(arr._data, ctx=arr._ctx)
    if stype == "row_sparse":
        v, i = _compress_rows(arr._data)
        return RowSparseNDArray(v, i, arr.shape, ctx=arr._ctx)
    if stype == "csr":
        if arr.ndim != 2:
            raise ValueError("csr requires 2D")
        d, i, p = _compress_csr(arr._data)
        return CSRNDArray(d, i, p, arr.shape, ctx=arr._ctx)
    raise ValueError("unknown stype %r" % stype)


# ------------------------------------------------------------- constructors

def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """reference `sparse.py` csr_matrix: (data, indices, indptr) triplet or
    dense/array-like source."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = _np.asarray(data)
        indices = _np.asarray(indices, dtype=_np.int64)
        indptr = _np.asarray(indptr, dtype=_np.int64)
        n_rows = len(indptr) - 1
        n_cols = (shape[1] if shape
                  else (int(indices.max()) + 1 if indices.size else 0))
        return CSRNDArray(data, indices, indptr, (n_rows, n_cols),
                          ctx=ctx, dtype=dtype)
    if isinstance(arg1, CSRNDArray):
        return arg1.copy()
    src = arg1._data if isinstance(arg1, NDArray) else _np.asarray(arg1)
    d, i, p = _compress_csr(src)
    return CSRNDArray(d, i, p, _np.asarray(src).shape, ctx=ctx, dtype=dtype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """reference `sparse.py` row_sparse_array: (data, indices) pair or
    dense/array-like source."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = _np.asarray(data)
        indices = _np.asarray(indices, dtype=_np.int64)
        if shape:
            full_shape = tuple(shape)
            if (data.size and data.shape[1:] != full_shape[1:]):
                raise ValueError(
                    "data shape %s inconsistent with shape %s"
                    % (data.shape, full_shape))
            if not data.size:
                data = data.reshape((0,) + full_shape[1:])
        else:
            n_rows = int(indices.max()) + 1 if indices.size else 0
            full_shape = (n_rows,) + data.shape[1:]
        return RowSparseNDArray(data, indices, full_shape, ctx=ctx,
                                dtype=dtype)
    if isinstance(arg1, RowSparseNDArray):
        return arg1.copy()
    src = arg1._data if isinstance(arg1, NDArray) else _np.asarray(arg1)
    v, i = _compress_rows(src)
    return RowSparseNDArray(v, i, _np.asarray(src).shape, ctx=ctx,
                            dtype=dtype)


def zeros(stype, shape, ctx=None, dtype=None):
    if stype == "row_sparse":
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        return RowSparseNDArray(
            jnp.zeros((0,) + shape[1:], dtype=dtype or "float32"),
            jnp.zeros((0,), dtype=jnp.int64), shape, ctx=ctx)
    if stype == "csr":
        shape = tuple(shape)
        return CSRNDArray(jnp.zeros((0,), dtype=dtype or "float32"),
                          jnp.zeros((0,), jnp.int64),
                          jnp.zeros((shape[0] + 1,), jnp.int64),
                          shape, ctx=ctx)
    return _dense_zeros(shape, ctx=ctx, dtype=dtype)


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    """reference `sparse.py` array: preserve the source's storage type."""
    if isinstance(source_array, CSRNDArray):
        return csr_matrix(source_array, ctx=ctx, dtype=dtype)
    if isinstance(source_array, RowSparseNDArray):
        return row_sparse_array(source_array, ctx=ctx, dtype=dtype)
    return _dense_array(source_array, ctx=ctx, dtype=dtype)


# ----------------------------------------------------- sparse-aware compute

def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse matmul (reference `src/operator/tensor/dot-inl.h` FComputeEx):

    - csr @ dense       -> gather + segment_sum (never densifies lhs)
    - csr.T @ dense     -> scatter-add  (reference dot(csr.T, dense) =
                           the embedding-gradient pattern, out row_sparse in
                           the reference; dense here)
    - rsp/dense fallbacks densify the sparse side.
    """
    if isinstance(lhs, CSRNDArray) and not transpose_b:
        data, indices, _ = lhs._payload()
        rows = lhs._row_ids()
        rv = rhs._data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        vec = rv.ndim == 1
        if vec:  # mat-vec: lift to (n, 1) so the gather/scale broadcasts
            rv = rv[:, None]
        if not transpose_a:
            gathered = rv[indices] * data[:, None].astype(rv.dtype)
            out = jax.ops.segment_sum(gathered, rows,
                                      num_segments=lhs.shape[0])
        else:
            # csr.T @ dense: out[indices[j]] += data[j] * rhs[row_ids[j]]
            gathered = rv[rows] * data[:, None].astype(rv.dtype)
            out = jnp.zeros((lhs.shape[1], rv.shape[1]), dtype=rv.dtype)
            out = out.at[indices].add(gathered)
        return NDArray(out[:, 0] if vec else out)
    lv = lhs._data if isinstance(lhs, NDArray) else jnp.asarray(lhs)
    rv = rhs._data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
    if transpose_a:
        lv = lv.T
    if transpose_b:
        rv = rv.T
    return NDArray(jnp.dot(lv, rv))


def retain(data, indices):
    """reference `sparse_retain` (`src/operator/tensor/sparse_retain-inl.h`):
    keep only the requested rows of a row_sparse array."""
    if not isinstance(data, RowSparseNDArray):
        raise TypeError("retain expects a RowSparseNDArray")
    keep = jnp.asarray(indices._data if isinstance(indices, NDArray)
                       else indices).astype(jnp.int64)
    values, idx = data._payload()
    # rows of `values` whose index is in `keep` survive
    mask = (idx[:, None] == keep[None, :]).any(axis=1)
    kept_np = _np.where(_np.asarray(mask))[0]
    return RowSparseNDArray(values[kept_np], idx[kept_np], data.shape,
                            ctx=data._ctx)


def add(lhs, rhs):
    """elementwise add preserving row_sparse when both sides are."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        lv, li = lhs._payload()
        rv, ri = rhs._payload()
        idx = jnp.asarray(_np.union1d(_np.asarray(li), _np.asarray(ri)))
        n = idx.shape[0]
        out = jnp.zeros((n,) + lhs.shape[1:], dtype=lhs._dtype_)
        pos_l = jnp.searchsorted(idx, li)
        pos_r = jnp.searchsorted(idx, ri)
        out = out.at[pos_l].add(lv).at[pos_r].add(rv.astype(lhs._dtype_))
        return RowSparseNDArray(out, idx, lhs.shape, ctx=lhs._ctx)
    lv = lhs._data if isinstance(lhs, NDArray) else lhs
    rv = rhs._data if isinstance(rhs, NDArray) else rhs
    return NDArray(lv + rv)
