"""MXNet-binary NDArray serialization.

Byte-compatible reader/writer for the reference ``.params`` / ``.nd`` container
(reference ``src/ndarray/ndarray.cc:1591-1852`` NDArray::Save/Load, dmlc::Stream
serializer framing).  Layout (all little-endian):

    uint64  header   = 0x112 (kMXAPINDArrayListMagic)
    uint64  reserved = 0
    uint64  count                       # vector<NDArray>
    count × NDArray record:
        uint32  magic = 0xF993fac9      # NDARRAY_V2_MAGIC (storage-type aware)
        int32   stype = 0               # kDefaultStorage (dense)
        int32   ndim; ndim × int64 dims # TShape::Save (tuple.h:704)
        int32   dev_type; int32 dev_id  # Context::Save (base.h:157)
        int32   type_flag               # mshadow/base.h:307 dtype enum
        raw data bytes (C order)
    uint64  count                       # vector<string> names
    count × (uint64 len; len bytes)

Legacy V1 (0xF993fac8, int64 dims) and pre-V1 (magic == ndim, uint32 dims)
records are also read, as is V3 (np-shape semantics, zero-size shapes kept).
"""
import struct

import numpy as np

NDARRAY_LIST_MAGIC = 0x112
NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9
NDARRAY_V3_MAGIC = 0xF993FACA

# mshadow type_flag <-> numpy dtype (reference 3rdparty/mshadow/mshadow/base.h:307)
_FLAG_TO_DTYPE = {
    0: np.dtype("float32"),
    1: np.dtype("float64"),
    2: np.dtype("float16"),
    3: np.dtype("uint8"),
    4: np.dtype("int32"),
    5: np.dtype("int8"),
    6: np.dtype("int64"),
    7: np.dtype("bool"),
}
_DTYPE_TO_FLAG = {v: k for k, v in _FLAG_TO_DTYPE.items()}


def _dtype_flag(dtype):
    dtype = np.dtype(dtype)
    flag = _DTYPE_TO_FLAG.get(dtype)
    if flag is None:
        raise TypeError(
            "dtype %s has no MXNet binary type_flag; cast first "
            "(bfloat16 arrays should be saved as float32)" % dtype)
    return flag


def _write_ndarray(fo, arr):
    arr = np.asarray(arr, order="C")
    if arr.dtype.name == "bfloat16":  # ml_dtypes bf16 — container has no flag for it
        arr = arr.astype(np.float32)
    # A V2 record with ndim==0 is the none-sentinel; genuine 0-d arrays only
    # exist under np-shape semantics, so emit a V3 record for them
    # (reference ndarray.cc:1592-1600).
    magic = NDARRAY_V3_MAGIC if arr.ndim == 0 else NDARRAY_V2_MAGIC
    fo.write(struct.pack("<I", magic))
    fo.write(struct.pack("<i", 0))                      # kDefaultStorage
    fo.write(struct.pack("<i", arr.ndim))
    fo.write(struct.pack("<%dq" % arr.ndim, *arr.shape))
    fo.write(struct.pack("<ii", 1, 0))                  # Context::CPU()
    fo.write(struct.pack("<i", _dtype_flag(arr.dtype)))
    fo.write(arr.tobytes())


def _read_exact(fi, n):
    buf = fi.read(n)
    if len(buf) != n:
        raise ValueError("invalid NDArray file format: truncated stream")
    return buf


def _read_shape(fi, dim_size):
    """Returns the dims tuple, or None for an unknown shape (ndim == -1,
    the reference's none/np-shape-unknown sentinel)."""
    (ndim,) = struct.unpack("<i", _read_exact(fi, 4))
    if ndim == -1:
        return None
    if ndim < 0 or ndim > 32:
        raise ValueError("invalid NDArray file format: bad ndim %d" % ndim)
    fmt = {8: "<%dq", 4: "<%dI"}[dim_size] % ndim
    return struct.unpack(fmt, _read_exact(fi, dim_size * ndim))


def _read_ndarray(fi):
    (magic,) = struct.unpack("<I", _read_exact(fi, 4))
    if magic in (NDARRAY_V2_MAGIC, NDARRAY_V3_MAGIC):
        (stype,) = struct.unpack("<i", _read_exact(fi, 4))
        if stype != 0:
            raise NotImplementedError(
                "sparse storage type %d in binary file not supported" % stype)
        shape = _read_shape(fi, 8)
        if shape is None or (magic == NDARRAY_V2_MAGIC and len(shape) == 0):
            return np.zeros((), dtype=np.float32)  # is_none() sentinel
    elif magic == NDARRAY_V1_MAGIC:
        shape = _read_shape(fi, 8)
        if shape is None or len(shape) == 0:
            return np.zeros((), dtype=np.float32)
    else:
        # pre-V1 legacy: magic itself is ndim, dims are uint32
        ndim = magic
        if ndim > 32:
            raise ValueError("invalid NDArray file format: bad magic 0x%x" % magic)
        shape = struct.unpack("<%dI" % ndim, _read_exact(fi, 4 * ndim))
        if ndim == 0:
            return np.zeros((), dtype=np.float32)
    _read_exact(fi, 8)  # Context (dev_type, dev_id) — always load to host
    (type_flag,) = struct.unpack("<i", _read_exact(fi, 4))
    dtype = _FLAG_TO_DTYPE.get(type_flag)
    if dtype is None:
        raise ValueError("unknown type_flag %d" % type_flag)
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    data = _read_exact(fi, dtype.itemsize * size)
    return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


def save_ndarrays(fname, arrays, names=None):
    """Write arrays in the reference binary list container.

    ``arrays`` items may be numpy arrays or objects with ``.asnumpy()``
    (host transfer happens one array at a time inside the write loop, so
    peak host memory is one array, not the whole checkpoint).  ``names``
    may be None/empty (positional list semantics, reference mx.nd.save of
    a list)."""
    names = list(names) if names else []
    if names and len(names) != len(arrays):
        raise ValueError("names/arrays length mismatch")
    with open(fname, "wb") as fo:
        fo.write(struct.pack("<QQ", NDARRAY_LIST_MAGIC, 0))
        fo.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_ndarray(fo, a.asnumpy() if hasattr(a, "asnumpy") else a)
        fo.write(struct.pack("<Q", len(names)))
        for n in names:
            b = n.encode("utf-8")
            fo.write(struct.pack("<Q", len(b)))
            fo.write(b)


def load_ndarrays(fname):
    """Read the reference binary list container -> (list[np.ndarray], list[str])."""
    with open(fname, "rb") as fi:
        header, _reserved = struct.unpack("<QQ", _read_exact(fi, 16))
        if header != NDARRAY_LIST_MAGIC:
            raise ValueError("invalid NDArray file format: bad header 0x%x" % header)
        (count,) = struct.unpack("<Q", _read_exact(fi, 8))
        arrays = [_read_ndarray(fi) for _ in range(count)]
        (nname,) = struct.unpack("<Q", _read_exact(fi, 8))
        names = []
        for _ in range(nname):
            (ln,) = struct.unpack("<Q", _read_exact(fi, 8))
            names.append(_read_exact(fi, ln).decode("utf-8"))
        if names and len(names) != len(arrays):
            raise ValueError("invalid NDArray file format: name count mismatch")
        return arrays, names


def is_mxnet_binary(fname):
    try:
        with open(fname, "rb") as fi:
            head = fi.read(8)
        return len(head) == 8 and struct.unpack("<Q", head)[0] == NDARRAY_LIST_MAGIC
    except OSError:
        return False
