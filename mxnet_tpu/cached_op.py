"""CachedOp: trace-once, compile-once graph execution (hybridize backend).

Role parity: reference ``src/imperative/cached_op.cc`` — Gluon's
``hybridize()`` traces ``hybrid_forward`` into an nnvm graph, then replays it
through a cached executor with static memory planning
(`cached_op.cc:1023 Forward`, `:861 StaticForward`, `:414 SetForwardGraph`);
when autograd is recording, the whole graph is recorded as ONE tape node
(`_CachedOp`, see `src/imperative/cached_op.cc:1077 DynamicBackward`).

TPU-native design: the graph IS an XLA program. We trace the Python callable
once per (shapes, dtypes, train-mode) signature with ``jax.jit`` — the
NDArray handles transparently carry tracers, so the whole eager op surface is
traceable with zero duplicated code. XLA then does what MXNet's passes did by
hand: memory planning (`src/nnvm/plan_memory.cc`), pointwise fusion
(`src/executor/pointwise_fusion_pass.cc`), op bulking, and static buffer
assignment (`static_alloc`/`static_shape` flags are accepted for API parity
and are effectively always-on under XLA).

Randomness: a fresh base PRNG key is an *argument* of the compiled program;
ops that need randomness split from it via ``random.push_trace_key`` — so
every execution of a cached graph sees new randomness while the trace stays
pure (the reference holds stateful cuDNN dropout descriptors in op state
instead).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

import jax

from . import _tape
from . import aot as _aot
from . import config as _config
from . import pcache as _pcache
from . import random as _random
from .observability import attribution as _attr
from .observability import telemetry as _telemetry
from .observability import tracer as _trace

__all__ = ["CachedOp", "cache_stats", "reset_cache_stats"]


def _np_dtype(name):
    """dtype-string (as stored in cache signatures) -> numpy dtype,
    including the ml_dtypes extras ("bfloat16") jax registers."""
    import numpy as _np
    try:
        return _np.dtype(name)
    except TypeError:
        import ml_dtypes
        return _np.dtype(getattr(ml_dtypes, str(name)))


def _active_sharding(val):
    """The input's NamedSharding when it is committed onto a multi-device
    mesh — the part of program identity the (shape, dtype) cache
    signature can't see. jit specializes the compiled SPMD program on
    these, so AOT export must re-lower with the SAME shardings or it
    would serialize a different (single-device) program than the one
    dispatch actually ran. Uncommitted / single-device inputs record
    None and keep the exact pre-sharding behavior."""
    s = getattr(val, "sharding", None)
    mesh = getattr(s, "mesh", None)
    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return None
    return s

# Process-wide executor-cache counters, aggregated across every CachedOp
# instance (the serving layer exports these through /metrics). A "miss" is
# an XLA compile; an "eviction" frees a compiled executable under the LRU
# bound (role of the reference's GetCachedOp registry bookkeeping).
_GLOBAL_STATS = {"hits": 0, "misses": 0, "evictions": 0}
_STATS_LOCK = threading.Lock()


def cache_stats():
    """Process-wide executor-cache counters summed over all CachedOps:
    ``{"hits", "misses", "evictions"}``. ``misses`` == number of XLA
    compiles issued by CachedOp dispatch since the last reset."""
    with _STATS_LOCK:
        return dict(_GLOBAL_STATS)


def reset_cache_stats():
    """Zero the process-wide counters (per-instance counters are reset by
    dropping the instance)."""
    with _STATS_LOCK:
        for k in _GLOBAL_STATS:
            _GLOBAL_STATS[k] = 0


class CachedOp:
    """Compile-cached executor for a callable over NDArrays.

    ``fn`` takes NDArray positional args and returns an NDArray or a
    list/tuple of NDArrays. Calls dispatch to a jitted pure function,
    cache-keyed on input (shape, dtype) signature and train mode —
    the moral equivalent of `SetForwardGraph`'s shape-match check
    (reference `src/imperative/cached_op.cc:414`).
    """

    def __init__(self, fn, static_alloc=False, static_shape=False,
                 inline_limit=2, forward_bulk_size=None,
                 backward_bulk_size=None, name="CachedOp", capacity=None):
        self._fn = fn
        self._name = name
        # flags kept for API parity (cached_op.h:33-52); XLA makes them no-ops
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape,
                           inline_limit=inline_limit,
                           forward_bulk_size=forward_bulk_size,
                           backward_bulk_size=backward_bulk_size)
        # LRU-bounded executor cache: each entry holds a compiled XLA
        # executable, so unbounded shape churn (dynamic batch/seq sizes)
        # is a memory leak without a cap. capacity <= 0 disables the bound.
        if capacity is None:
            capacity = _config.get("MXNET_CACHED_OP_CAPACITY")
        self._capacity = int(capacity)
        self._cache = OrderedDict()
        # per-signature committed input shardings (mesh lanes only; see
        # _active_sharding) — what serialize() re-lowers against
        self._shardings = {}
        self._stats = {"hits": 0, "misses": 0, "evictions": 0,
                       "aot_loads": 0}
        # the serving engine dispatches one CachedOp from many HTTP threads:
        # every _cache/_stats mutation happens under this lock. Compiles run
        # OUTSIDE it (an XLA compile can take seconds; serializing compiles
        # of different signatures would stall every other thread) — two
        # threads racing the same cold signature may both compile, and the
        # loser's executable is simply dropped on insert.
        self._dispatch_lock = threading.Lock()

    def cache_stats(self):
        """This instance's executor-cache counters plus occupancy:
        ``{"size", "capacity", "hits", "misses", "evictions",
        "aot_loads"}`` — ``aot_loads`` counts executables installed
        from serialized artifacts (zero XLA compiles)."""
        with self._dispatch_lock:
            out = dict(self._stats)
            out["size"] = len(self._cache)
        out["capacity"] = self._capacity
        return out

    def flops_per_call(self):
        """Analytic FLOPs of each resident executable, keyed by the full
        cache signature — input shapes/dtypes AND train mode, since the
        same shapes compile distinct train/eval executables (XLA cost
        model, computed at compile time): the per-executable number the
        MFU accounting multiplies by dispatch count. 0.0 entries mean
        the backend's cost model was unavailable (MFU is then
        underreported, never fabricated)."""
        with self._dispatch_lock:
            return {"%s|train=%s" % (sig[0], sig[1]): entry[4]
                    for sig, entry in self._cache.items()}

    def bytes_per_call(self):
        """Analytic bytes accessed per execution of each resident
        executable (XLA cost model, same keying as
        :meth:`flops_per_call`) — the denominator of the roofline
        arithmetic intensity. 0.0 = cost model unavailable (the
        executable classifies as ``unknown``, never a guess)."""
        with self._dispatch_lock:
            return {"%s|train=%s" % (sig[0], sig[1]): entry[6]
                    for sig, entry in self._cache.items()}

    def clear(self):
        """Drop every compiled executable (the LRU empties; counters
        keep their history). Unloading a served model must free its XLA
        programs — a retired fleet version holding ``len(buckets)``
        executables through this cache would be a device-memory leak."""
        with self._dispatch_lock:
            self._cache.clear()
            self._shardings.clear()

    def _signature(self, args):
        return (tuple((a.shape, str(a.dtype)) for a in args),
                _tape.is_training())

    def _make_pure(self, train):
        """The jit-able pure wrapper over ``self._fn`` at an explicit
        train mode (dispatch passes the current mode; serialize/
        deserialize pass the mode stored in the cache signature).
        Returns ``(pure, n_out_box, aux_handles_box)`` — the boxes fill
        on first trace."""
        from .ndarray.ndarray import NDArray
        fn = self._fn
        n_out_box = []
        aux_handles_box = []

        def pure(rng_key, *vals):
            nds = [NDArray(v) for v in vals]
            _random.push_trace_key(rng_key)
            prev_rec = _tape.set_recording(False)
            prev_train = _tape.set_training(train)
            sink = _tape.push_aux_sink()
            try:
                outs = fn(*nds)
            finally:
                _tape.pop_aux_sink()
                _tape.set_training(prev_train)
                _tape.set_recording(prev_rec)
                _random.pop_trace_key()
            multi = isinstance(outs, (list, tuple))
            outs_t = tuple(outs) if multi else (outs,)
            if not n_out_box:
                n_out_box.append((len(outs_t), multi))
                aux_handles_box.append([h for h, _ in sink])
            # aux writes (e.g. BatchNorm moving stats) ride as extra outputs
            return tuple(o._data for o in outs_t) + tuple(v for _, v in sink)

        return pure, n_out_box, aux_handles_box

    def _compile(self, args):
        train = _tape.is_training()
        pure, n_out_box, aux_handles_box = self._make_pure(train)
        jitted = jax.jit(pure)
        # force trace now so n_out is known before first real dispatch;
        # with FLOPs accounting on, the forcing trace is lower() instead
        # of eval_shape() so the analytic FLOPs (XLA cost model, cached
        # on the cache entry — every dispatch then feeds the process
        # FlopsMeter at the cost of one float add, the source behind the
        # live mxtpu_mfu_percent / mxtpu_flops_total series) ride the
        # SAME trace rather than paying a second one
        specs = [jax.ShapeDtypeStruct(a.shape, a._data.dtype)
                 for a in args]
        # cost analysis is gated on MXNET_TELEMETRY_FLOPS alone: with it
        # off, attribution still measures dispatch wall but reports its
        # rows as `unknown` (no analytic numbers, no guessed ones)
        flops = 0.0
        nbytes = 0.0
        if int(_config.get("MXNET_TELEMETRY_FLOPS") or 0):
            try:
                lowered = jitted.lower(jax.random.PRNGKey(0), *specs)
            except Exception:  # fall back to the plain forcing trace
                jax.eval_shape(jitted, jax.random.PRNGKey(0), *specs)
            else:
                try:
                    cost = lowered.cost_analysis()
                    flops = float((cost or {}).get("flops", 0.0) or 0.0)
                    # "bytes accessed" (HBM traffic per execution) rides
                    # the same analysis: the roofline denominator
                    nbytes = float((cost or {}).get("bytes accessed",
                                                    0.0) or 0.0)
                except Exception:  # cost model unavailable on this backend
                    flops = 0.0
                    nbytes = 0.0
        else:
            jax.eval_shape(jitted, jax.random.PRNGKey(0), *specs)
        n_out, multi = n_out_box[0]
        return (jitted, n_out, multi, aux_handles_box[0], flops, False,
                nbytes)

    # ---- AOT export / load (cold-start: compile in CI, ship bytes) --------
    def _specs_for(self, sig, shardings=None):
        shapes, _ = sig
        if shardings is None:
            shardings = (None,) * len(shapes)
        return [jax.ShapeDtypeStruct(tuple(shape), _np_dtype(dtype),
                                     sharding=s)
                for (shape, dtype), s in zip(shapes, shardings)]

    def input_shardings(self, sig):
        """The committed input shardings signature ``sig`` was compiled
        against (None per arg on single-device lanes)."""
        with self._dispatch_lock:
            return self._shardings.get(sig)

    def record_shardings(self, sig, shardings):
        """Pre-seed ``sig``'s committed input shardings. Sharded engines
        call this after an AOT load (deserialized machine code carries
        no jax-level shardings), so a later re-export still lowers the
        same SPMD program instead of a single-device one."""
        with self._dispatch_lock:
            self._shardings[sig] = tuple(shardings)

    def serialize(self):
        """Capture every resident executable's *program* as
        PJRT-serialized bytes: a list of records for
        :func:`mxnet_tpu.aot.write_artifact`, keyed by the exact cache
        signature (shapes, dtypes, train mode) each was compiled under.

        Export re-lowers and compiles each signature through the jax AOT
        API (the traced-dispatch path's executable isn't directly
        extractable), so exporting costs one compile per signature —
        that is the point: the export runs ONCE in CI, and every serving
        restart after it compiles nothing. With the persistent compile
        cache enabled the re-compile here is itself a disk hit."""
        with self._dispatch_lock:
            sigs = [(sig, entry[4], entry[6], self._shardings.get(sig))
                    for sig, entry in self._cache.items()]
        records = []
        for sig, flops, nbytes, shardings in sigs:
            train = sig[1]
            pure, _n_out_box, _aux_box = self._make_pure(train)
            compiled = jax.jit(pure).lower(
                jax.random.PRNGKey(0),
                *self._specs_for(sig, shardings)).compile()
            blob, in_tree, out_tree = _aot.serialize_compiled(compiled)
            records.append({"signature": sig, "train": train,
                            "flops": flops, "bytes": nbytes,
                            "blob": blob,
                            "in_tree": in_tree, "out_tree": out_tree})
        return records

    def deserialize(self, records):
        """Install serialized executables (``mxnet_tpu.aot`` records)
        into the cache WITHOUT compiling: each record's program loads as
        machine code, and an abstract ``eval_shape`` trace (pure Python,
        no XLA) recovers the output arity and aux-state handles the
        dispatch path needs. Returns the number of executables
        installed; raises :class:`~mxnet_tpu.aot.ArtifactError` on a
        corrupt record — fingerprint gating belongs to the caller
        (``InferenceEngine.load_artifacts``), which turns it into a
        warn-once fallback instead of a crash."""
        loaded = 0
        evicted = 0
        for rec in records:
            sig = rec["signature"]
            train = bool(sig[1])
            specs = self._specs_for(sig)
            pure, n_out_box, aux_handles_box = self._make_pure(train)
            jitted = jax.jit(pure)
            jax.eval_shape(jitted, jax.random.PRNGKey(0), *specs)
            n_out, multi = n_out_box[0]
            exe = _aot.deserialize_compiled(rec["blob"], rec["in_tree"],
                                            rec["out_tree"])
            entry = (exe, n_out, multi, aux_handles_box[0],
                     float(rec.get("flops") or 0.0), True,
                     float(rec.get("bytes") or 0.0))
            with self._dispatch_lock:
                self._cache[sig] = entry
                self._cache.move_to_end(sig)
                self._stats["aot_loads"] = \
                    self._stats.get("aot_loads", 0) + 1
                if self._capacity > 0:
                    while len(self._cache) > self._capacity:
                        self._cache.popitem(last=False)
                        evicted += 1
                        self._stats["evictions"] += 1
            loaded += 1
        if evicted:
            with _STATS_LOCK:
                _GLOBAL_STATS["evictions"] += evicted
        if loaded:
            _pcache.note_aot_load(loaded)
        return loaded

    def __call__(self, *args, **kwargs):
        import jax as _jax
        from .ndarray.ndarray import NDArray

        args = [a if isinstance(a, NDArray) else NDArray(a) for a in args]
        # Inside an enclosing trace (a hybridized parent block), inline this
        # op's body into the parent program instead of nesting jit — matches
        # the reference where the whole net becomes ONE CachedOp graph, and
        # keeps aux-state writes flowing to the outermost sink.
        if any(isinstance(a._data, _jax.core.Tracer) for a in args):
            return self._fn(*args)
        sig = self._signature(args)
        recording = _tape.is_recording()
        with self._dispatch_lock:
            entry = self._cache.get(sig)
            if entry is not None and entry[5] and recording:
                # an AOT-loaded executable is machine code — it can't be
                # retraced for the autograd tape. Recording dispatch of
                # an AOT entry recompiles fresh (counted as the miss it
                # is) and replaces the entry; serving never records.
                entry = None
            elif entry is not None:
                self._cache.move_to_end(sig)
                self._stats["hits"] += 1
                if entry[4]:
                    self._stats["flops"] = \
                        self._stats.get("flops", 0.0) + entry[4]
        bucket = args[0].shape[0] if args and args[0].shape else None
        compiled_now = entry is None
        if entry is None:
            # compile outside the lock (see __init__); the span makes XLA
            # compiles first-class timeline citizens, labeled with the
            # shape bucket (leading dim of the first input) that triggered
            # them — the classic "why was THIS request 2s?" answer
            t_c0 = time.perf_counter()
            shards = tuple(_active_sharding(a._data) for a in args)
            if not any(s is not None for s in shards):
                shards = None
            with _trace.span("cachedop.compile", op=self._name,
                             bucket=bucket, signature=str(sig[0])):
                compiled = self._compile(args)
            _attr.flight_note("compile", op=self._name, bucket=bucket,
                              wall_ms=(time.perf_counter() - t_c0) * 1e3)
            evicted = 0
            with self._dispatch_lock:
                entry = self._cache.get(sig)
                if shards is not None:
                    self._shardings[sig] = shards
                if entry is None or (entry[5] and recording):
                    # we won (or were alone, or are replacing an AOT
                    # entry with a traceable one): publish our executable
                    self._cache[sig] = entry = compiled
                else:
                    # a racing thread published first — use theirs, drop
                    # ours; still a miss (an XLA compile really happened)
                    self._cache.move_to_end(sig)
                self._stats["misses"] += 1
                if entry[4]:
                    self._stats["flops"] = \
                        self._stats.get("flops", 0.0) + entry[4]
                if self._capacity > 0:
                    while len(self._cache) > self._capacity:
                        self._cache.popitem(last=False)
                        evicted += 1
                self._stats["evictions"] += evicted
            with _STATS_LOCK:
                _GLOBAL_STATS["misses"] += 1
                _GLOBAL_STATS["evictions"] += evicted
        else:
            with _STATS_LOCK:
                _GLOBAL_STATS["hits"] += 1
        # per-op flops already accounted inside the hit/miss critical
        # sections above — no second lock acquisition on the hot path
        jitted, n_out, multi, aux_handles, flops, aot, nbytes = entry
        if flops:
            _telemetry.add_flops(flops)

        key = _random.next_key()
        vals = [a._data for a in args]
        # dispatch wall pair for the roofline attribution: on a
        # synchronous backend this is execution time; under async
        # dispatch it can understate execution (enqueue-only), making
        # the derived achieved-FLOP/s an overstatement — see the
        # attribution.py module docstring for the reading guidance
        t_d0 = time.perf_counter()
        try:
            out_vals = jitted(key, *vals)
        except Exception as exc:  # noqa: BLE001 — AOT aval drift only
            if not aot:
                raise
            # a loaded executable refused these exact arguments (aval
            # drift the shape/dtype signature can't see, or a backend
            # that rejected the deserialized program at dispatch):
            # recompile fresh ONCE, replace the entry, and count the
            # fallback — a shipped artifact must degrade to a compile,
            # never to a serving error
            _pcache.note_aot_fallback(
                "%s: %s" % (type(exc).__name__, exc),
                where="CachedOp(%s)" % self._name)
            with _trace.span("cachedop.compile", op=self._name,
                             bucket=bucket, signature=str(sig[0])):
                entry = self._compile(args)
            with self._dispatch_lock:
                self._cache[sig] = entry
                self._cache.move_to_end(sig)
                self._stats["misses"] += 1
            with _STATS_LOCK:
                _GLOBAL_STATS["misses"] += 1
            jitted, n_out, multi, aux_handles, flops, aot, nbytes = entry
            compiled_now = True
            t_d0 = time.perf_counter()
            out_vals = jitted(key, *vals)
        # the FIRST dispatch after a miss pays the jit wrapper's retrace
        # + backend compile (the forcing trace in _compile lower()s but
        # never .compile()s) — its wall is compile, not dispatch, and
        # would rank compile cost in the roofline table; it registers
        # the executable (calls/FLOPs/AI) with wall_s=None, and only
        # warm dispatches contribute measured time
        _attr.record_dispatch(self._name,
                              "%s|train=%s" % (sig[0], sig[1]),
                              bucket, flops, nbytes,
                              None if compiled_now
                              else time.perf_counter() - t_d0)
        for h, v in zip(aux_handles, out_vals[n_out:]):
            h._data = v
        out_vals = out_vals[:n_out]

        node = None
        if _tape.is_recording():
            parents = [_tape.Const(key)]
            for a in args:
                n = a._ag_node
                if n is None:
                    parents.append(_tape.Const(a._data))
                else:
                    parents.append(n if isinstance(n, tuple) else (n, 0))
            node = _tape.OpNode(jitted, parents, n_out, {}, self._name)

        results = []
        for i, v in enumerate(out_vals):
            arr = NDArray(v, ctx=args[0]._ctx if args else None)
            if node is not None:
                arr._ag_node = (node, i)
            results.append(arr)
        return results if multi else results[0]
