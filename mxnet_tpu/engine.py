"""Engine control surface (reference ``python/mxnet/engine.py`` over
`src/engine/`: bulk scope + engine type).

TPU-native: XLA *is* the engine (SURVEY §7) — program order + async PJRT
dispatch replace the dependency scheduler's var/opr queues
(`src/engine/threaded_engine.h:282`). The knobs are kept for API parity:
`bulk` is a no-op scope (XLA fuses/bulks on its own), and the env var
`MXNET_ENGINE_TYPE=NaiveEngine` maps to blocking dispatch (every op result
synchronized immediately — the reference's serializing debug engine,
`src/engine/naive_engine.cc`).
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = [int(os.environ.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15))]
_naive = [os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"]


def set_bulk_size(size):
    """reference engine.py set_bulk_size (MXEngineSetBulkSize)."""
    prev = _bulk_size[0]
    _bulk_size[0] = size
    return prev


@contextlib.contextmanager
def bulk(size):
    """reference engine.py bulk scope."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def is_naive():
    return _naive[0]


def set_naive(flag=True):
    """Blocking debug dispatch (MXNET_ENGINE_TYPE=NaiveEngine)."""
    _naive[0] = bool(flag)
