"""Legacy mx.rnn namespace (reference ``python/mxnet/rnn/``: BucketSentenceIter,
legacy symbolic RNN cells). The cell classes alias the gluon implementations
(the reference's legacy cells predate Gluon; one implementation serves both
surfaces here)."""
from .io import BucketSentenceIter, encode_sentences
from ..gluon.rnn import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
                         BidirectionalCell, DropoutCell, ZoneoutCell,
                         ResidualCell)
