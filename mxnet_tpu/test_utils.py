"""Test utilities.

Parity surface: reference ``python/mxnet/test_utils.py`` —
assert_almost_equal :534, check_numeric_gradient :981 (central finite
differences), default_context :58, check_consistency (cross-device oracle).
On TPU the cross-device oracle is XLA-CPU vs the chip; the numeric-gradient
oracle checks the tape+jax.vjp backward against finite differences.
"""
from __future__ import annotations

import numpy as np

from .context import Context, current_context
from .ndarray.ndarray import NDArray, array
from . import autograd as ag

_default_ctx = None


def default_context() -> Context:
    return _default_ctx or current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg="%s vs %s" % names)


def almost_equal(a, b, rtol=1e-5, atol=1e-8):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    return np.allclose(a, b, rtol=rtol, atol=atol)


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    data = np.random.uniform(-1, 1, size=shape).astype(dtype or np.float32)
    out = array(data, ctx=ctx)
    if stype != "default":
        out = out.tostype(stype)
    return out


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-3, atol=1e-4):
    """Central finite differences vs tape backward
    (reference `python/mxnet/test_utils.py:981`)."""
    import jax
    try:
        on_accel = any(d.platform not in ("cpu",) for d in jax.devices())
    except RuntimeError:
        on_accel = False
    if on_accel:
        # f32 central differences on the accelerator carry ~1e-3 rel
        # truncation+rounding; the reference's GPU FD checks run at 1e-2
        # (test_utils.py check_numeric_gradient GPU defaults)
        rtol, atol = max(rtol, 1e-2), max(atol, 1e-3)
    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with ag.record():
        y = fn(*inputs)
    y.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for i, x in enumerate(inputs):
        # ascontiguousarray: the TPU-tunnel backend materialises device
        # arrays F-contiguous, and ravel() of an F-order array is a COPY —
        # the nflat[j] writes below would silently vanish (the
        # docs/consistency_tpu.md all-zero-numeric failure class)
        base = np.ascontiguousarray(x.asnumpy(), dtype=np.float64)
        num = np.zeros(base.shape, dtype=np.float64)
        flat = base.ravel()
        nflat = num.ravel()
        for j in range(flat.size):
            orig = flat[j]
            _set_flat(x, base, j, orig + eps)
            fp = float(fn(*inputs).asnumpy())
            _set_flat(x, base, j, orig - eps)
            fm = float(fn(*inputs).asnumpy())
            _set_flat(x, base, j, orig)
            flat[j] = orig
            nflat[j] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(analytic[i], num, rtol=rtol, atol=atol,
                                   err_msg="gradient mismatch for input %d" % i)


def _set_flat(x, base, j, val):
    import jax.numpy as jnp
    b = base.copy()
    b.ravel()[j] = val
    x._data = jnp.asarray(b.astype(np.asarray(x._data).dtype))
    return x._data


def check_consistency(fn, inputs, ctxs=None, rtol=1e-4, atol=1e-6):
    """Cross-device same-op comparison (reference check_consistency — GPU vs
    CPU oracle; here each ctx in ctxs, default cpu-only)."""
    outs = []
    for ctx in (ctxs or [Context("cpu", 0)]):
        with ctx:
            ins = [x.as_in_context(ctx) for x in inputs]
            outs.append(fn(*ins).asnumpy())
    for o in outs[1:]:
        np.testing.assert_allclose(outs[0], o, rtol=rtol, atol=atol)
    return outs


class DummyIter:
    def __init__(self, batches):
        self._batches = batches

    def __iter__(self):
        return iter(self._batches)


def same(a, b):
    """Exact array equality (reference test_utils.py same)."""
    return np.array_equal(np.asarray(a.asnumpy() if hasattr(a, "asnumpy")
                                     else a),
                          np.asarray(b.asnumpy() if hasattr(b, "asnumpy")
                                     else b))


def rand_shape_2d(dim0=10, dim1=10):
    """Random 2D shape (reference test_utils.py rand_shape_2d)."""
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim).tolist())


def list_gpus():
    """Enumerate accelerator ordinals (reference test_utils.py list_gpus —
    here, TPU chips; empty on a CPU-only host)."""
    import jax
    try:
        return [d.id for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError:
        return []
