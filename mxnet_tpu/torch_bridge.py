"""Torch interop (``mx.th`` / reference ``python/mxnet/torch.py``).

Parity surface: the reference bridges Torch7 tensor functions into MXNet
(`torch.py:37` _make_torch_function over a C glue layer) so users can mix
torch ops with NDArrays.

TPU-native design: PyTorch (CPU) interops through dlpack/numpy — no glue
runtime. ``to_torch``/``from_torch`` convert NDArray <-> torch.Tensor
(zero-copy via dlpack where both sides allow it), and ``torch_function``
wraps any torch callable so it consumes/produces NDArrays, which is what
the reference's generated `mx.th.*` namespace did for Torch7."""
from __future__ import annotations

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["to_torch", "from_torch", "torch_function"]


def _torch():
    try:
        import torch
        return torch
    except ImportError as e:  # pragma: no cover
        raise MXNetError("torch is not available: %s" % e)


def to_torch(arr):
    """NDArray -> torch.Tensor (dlpack when possible, else host copy)."""
    torch = _torch()
    if not isinstance(arr, NDArray):
        raise TypeError("expected NDArray")
    try:
        return torch.from_dlpack(arr._data)
    except Exception:
        return torch.from_numpy(arr.asnumpy())


def from_torch(tensor, ctx=None):
    """torch.Tensor -> NDArray."""
    torch = _torch()
    if not isinstance(tensor, torch.Tensor):
        raise TypeError("expected torch.Tensor")
    t = tensor.detach().cpu().contiguous()
    return NDArray(t.numpy(), ctx=ctx)


def torch_function(fn):
    """Wrap a torch callable to take/return NDArrays (the role of the
    reference's generated mx.th.* functions)::

        mx_conv = mx.th.torch_function(torch.nn.functional.conv2d)
        y = mx_conv(x, w)           # x, w, y are NDArrays
    """
    torch = _torch()

    def wrapped(*args, **kwargs):
        def conv(a):
            return to_torch(a) if isinstance(a, NDArray) else a
        out = fn(*[conv(a) for a in args],
                 **{k: conv(v) for k, v in kwargs.items()})
        if isinstance(out, torch.Tensor):
            return from_torch(out)
        if isinstance(out, (tuple, list)):
            return type(out)(from_torch(o) if isinstance(o, torch.Tensor)
                             else o for o in out)
        return out

    wrapped.__name__ = getattr(fn, "__name__", "torch_function")
    return wrapped
