"""DataLoader.

Parity surface: reference ``python/mxnet/gluon/data/dataloader.py`` —
multiprocessing workers + shared-memory NDArray transport
(`dataloader.py:28-111` ConnectionWrapper/SimpleQueue rebuild machinery over
`src/storage/cpu_shared_storage_manager.h`).

TPU-native design: batches are assembled host-side in numpy and land on
device in one transfer per batch. Parallelism uses a thread pool rather than
fork-per-worker: decode/augment is numpy (releases the GIL for the heavy
parts) and, critically, forked children would try to re-initialize the TPU
client — the same reason JAX programs avoid fork. `num_workers` maps to
threads; the prefetch queue double-buffers ahead of the device.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ...ndarray import ndarray as _nd
from ...ndarray.ndarray import NDArray
from . import sampler as _sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py:128)."""
    if isinstance(data[0], NDArray):
        return _nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return _nd.array(data, dtype=data.dtype if data.dtype != np.float64
                     else np.float32)


class DataLoader:
    """reference dataloader.py:169."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = _sampler.RandomSampler(len(dataset))
                else:
                    sampler = _sampler.SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    yield self._batchify_fn(
                        [self._dataset[idx] for idx in batch])
            return same_process_iter()
        return _MultiWorkerIter(self)

    def __len__(self):
        return len(self._batch_sampler)


class _MultiWorkerIter:
    """Thread-pool prefetching iterator (role of the reference's
    fork-based _MultiWorkerIter, dataloader.py:417)."""

    def __init__(self, loader):
        self._loader = loader
        self._batches = list(loader._batch_sampler)
        self._n = len(self._batches)
        self._sent = 0
        self._got = 0
        self._results = {}
        self._out_q = queue.Queue()
        self._task_q = queue.Queue()
        depth = max(1, loader._prefetch)
        for _ in range(loader._num_workers):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
        for _ in range(min(depth, self._n)):
            self._dispatch()

    def _worker(self):
        while True:
            item = self._task_q.get()
            if item is None:
                return
            i, idxs = item
            try:
                batch = self._loader._batchify_fn(
                    [self._loader._dataset[idx] for idx in idxs])
                self._out_q.put((i, batch, None))
            except Exception as e:  # propagate to consumer
                self._out_q.put((i, None, e))

    def _dispatch(self):
        if self._sent < self._n:
            self._task_q.put((self._sent, self._batches[self._sent]))
            self._sent += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._got >= self._n:
            for _ in range(self._loader._num_workers):
                self._task_q.put(None)
            raise StopIteration
        while self._got not in self._results:
            i, batch, err = self._out_q.get(timeout=self._loader._timeout)
            self._results[i] = (batch, err)
        batch, err = self._results.pop(self._got)
        self._got += 1
        self._dispatch()
        if err is not None:
            raise err
        return batch

    next = __next__
