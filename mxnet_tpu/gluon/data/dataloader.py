"""DataLoader.

Parity surface: reference ``python/mxnet/gluon/data/dataloader.py`` —
multiprocessing workers + shared-memory NDArray transport
(`dataloader.py:28-111` ConnectionWrapper/SimpleQueue rebuild machinery over
`src/storage/cpu_shared_storage_manager.h`).

TPU-native design: batches are assembled host-side in numpy and land on
device in one transfer per batch. Parallelism uses a thread pool rather than
fork-per-worker: decode/augment is numpy (releases the GIL for the heavy
parts) and, critically, forked children would try to re-initialize the TPU
client — the same reason JAX programs avoid fork. `num_workers` maps to
threads; the prefetch queue double-buffers ahead of the device.
"""
from __future__ import annotations

import itertools
import queue
import threading
import weakref

import numpy as np

from ...ndarray import ndarray as _nd
from ...ndarray.ndarray import NDArray
from . import sampler as _sampler

__all__ = ["DataLoader", "DataLoaderSkipLimit", "default_batchify_fn"]

# distinct pin_memory stats name per loader (datafeed registry is
# latest-wins per name; train + val loaders must both stay visible)
_pin_seq = itertools.count()


class DataLoaderSkipLimit(RuntimeError):
    """``error_policy="skip"`` hit its bad-sample cap
    (``MXNET_DATALOADER_MAX_SKIPS``): this is data-wide corruption, not a
    few bad records — failing loudly beats silently training on a
    shrinking dataset. ``__cause__`` is the last sample error."""


# process-wide skipped-sample counter, exported to the profiler aggregate
# table (row ``guardrails.dataloader.skipped``) so silent data loss is
# never actually silent
_skip_lock = threading.Lock()
_skipped_total = 0


def _count_skip(n=1):
    global _skipped_total
    with _skip_lock:
        _skipped_total += n


def _profiler_rows():
    with _skip_lock:
        return {"guardrails.dataloader.skipped": (_skipped_total, 0.0)}


from ...resilience._stats import export_rows as _export_rows  # noqa: E402

_export_rows(_profiler_rows)


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py:128)."""
    if isinstance(data[0], NDArray):
        return _nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return _nd.array(data, dtype=data.dtype if data.dtype != np.float64
                     else np.float32)


class DataLoader:
    """reference dataloader.py:169."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120,
                 error_policy="raise", max_skips=None):
        """``pin_memory``: the reference staged batches into page-locked
        host memory so the device copy could run async with compute; here
        the same promise — "the transfer is already underway when the
        consumer asks" — is kept by pre-staging batches through a
        :class:`~mxnet_tpu.parallel.datafeed.DeviceFeed` ring (depth =
        ``prefetch`` if set, else ``MXNET_DATAFEED_DEPTH``), yielding
        device-backed NDArrays. ``pin_device_id`` is accepted for API
        parity (single default device per process here). One staging ring
        is live per loader: starting a new epoch retires the previous
        ring (so a mid-epoch ``break`` can't strand staged buffers) —
        iterate a pinned loader from one place at a time.

        ``error_policy``: what to do when a sample's ``__getitem__`` or
        its batchify raises — ``"raise"`` (reference behavior: the error
        propagates to the consumer) or ``"skip"`` (drop the bad sample,
        count it in the ``guardrails.dataloader.skipped`` profiler row,
        serve the rest of the batch). ``max_skips`` caps skipped samples
        per iteration (default ``MXNET_DATALOADER_MAX_SKIPS``; negative =
        unbounded); past the cap a :class:`DataLoaderSkipLimit` is raised
        — a few corrupt records are survivable, a corrupt dataset is not.
        """
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if error_policy not in ("raise", "skip"):
            raise ValueError("error_policy must be 'raise' or 'skip', got "
                             "%r" % (error_policy,))
        self._error_policy = error_policy
        if max_skips is None:
            from ... import config as _config
            max_skips = _config.get("MXNET_DATALOADER_MAX_SKIPS")
        self._max_skips = int(max_skips)
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = _sampler.RandomSampler(len(dataset))
                else:
                    sampler = _sampler.SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def _load_batch(self, idxs, budget):
        """Fetch + batchify one batch honoring ``error_policy``. Returns
        the batch, or None when every sample in it was skipped."""
        if self._error_policy == "raise":
            return self._batchify_fn([self._dataset[idx] for idx in idxs])
        samples = []
        for idx in idxs:
            try:
                samples.append(self._dataset[idx])
            except Exception as e:  # noqa: BLE001 — the policy's whole point
                budget.spend(1, e)
        if not samples:
            return None
        try:
            return self._batchify_fn(samples)
        except Exception:  # noqa: BLE001 — attribute the failure per sample
            good = []
            for s in samples:
                try:
                    self._batchify_fn([s])
                    good.append(s)
                except Exception as e:  # noqa: BLE001
                    budget.spend(1, e)
            if not good:
                return None
            # a mix that STILL fails jointly (shape-incompatible but each
            # fine alone) is a batchify bug, not a bad sample: propagate
            return self._batchify_fn(good)

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                budget = _SkipBudget(self._max_skips)
                for batch in self._batch_sampler:
                    out = self._load_batch(batch, budget)
                    if out is not None:
                        yield out
            base = same_process_iter()
        else:
            base = _MultiWorkerIter(self)
        if not self._pin_memory:
            return base
        # pin_memory: the reference copied batches into page-locked host
        # buffers so the engine's async cudaMemcpy could overlap compute
        # (reference dataloader.py:431 _as_in_context pinned path). The
        # TPU-native equivalent of "transfer already underway when the
        # consumer asks" is a DeviceFeed ring: batches are dispatched to
        # device buffers ahead of consumption and come back as
        # device-backed NDArrays in the loader's own batch structure.
        from ...parallel.datafeed import DeviceFeed
        depth = self._prefetch if self._prefetch > 0 else None
        # retire the previous epoch's feed (if any): an abandoned mid-epoch
        # ring must not keep its stager thread parked on a full queue
        last_ref = getattr(self, "_pin_feed", None)
        last = last_ref() if last_ref is not None else None
        if last is not None:
            last.close()
        # per-loader stats name: concurrent pinned loaders (train + val)
        # must not evict each other's rows from the latest-wins registry
        name = getattr(self, "_pin_name", None)
        if name is None:
            name = self._pin_name = "dataloader.%d" % next(_pin_seq)
        feed = DeviceFeed(base, mesh=None, output="batch", depth=depth,
                          timeout=self._timeout, name=name)
        # WEAK ref: the feed's lifetime belongs to the epoch's consumer,
        # not to this loader — a strong ref here would make the stager
        # (whose source closure reaches the loader) keep an abandoned
        # feed alive, and closing it from a __del__ that can fire on the
        # stager's own thread deadlocked the anonymous-loader idiom
        # `for batch in DataLoader(..., pin_memory=True)`
        self._pin_feed = weakref.ref(feed)
        return iter(feed)

    def __len__(self):
        return len(self._batch_sampler)


class _SkipBudget:
    """Per-iteration skip accounting shared across worker threads: counts
    into the process-wide profiler row and enforces the loud-failure cap."""

    def __init__(self, cap):
        self._lock = threading.Lock()
        self._cap = cap
        self.count = 0

    def spend(self, n, err):
        with self._lock:
            self.count += n
            count = self.count
        _count_skip(n)
        if self._cap >= 0 and count > self._cap:
            raise DataLoaderSkipLimit(
                "DataLoader skipped %d samples (cap %d, "
                "MXNET_DATALOADER_MAX_SKIPS) — data-wide corruption?"
                % (count, self._cap)) from err


class _MultiWorkerIter:
    """Thread-pool prefetching iterator (role of the reference's
    fork-based _MultiWorkerIter, dataloader.py:417)."""

    def __init__(self, loader):
        self._loader = loader
        self._batches = list(loader._batch_sampler)
        self._n = len(self._batches)
        self._sent = 0
        self._got = 0
        self._results = {}
        self._out_q = queue.Queue()
        self._task_q = queue.Queue()
        self._budget = _SkipBudget(loader._max_skips)
        depth = max(1, loader._prefetch)
        for _ in range(loader._num_workers):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
        for _ in range(min(depth, self._n)):
            self._dispatch()

    def _worker(self):
        while True:
            item = self._task_q.get()
            if item is None:
                return
            i, idxs = item
            try:
                batch = self._loader._load_batch(idxs, self._budget)
                self._out_q.put((i, batch, None))
            except Exception as e:  # propagate to consumer
                self._out_q.put((i, None, e))

    def _dispatch(self):
        if self._sent < self._n:
            self._task_q.put((self._sent, self._batches[self._sent]))
            self._sent += 1

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._got >= self._n:
                for _ in range(self._loader._num_workers):
                    self._task_q.put(None)
                raise StopIteration
            while self._got not in self._results:
                i, batch, err = self._out_q.get(
                    timeout=self._loader._timeout)
                self._results[i] = (batch, err)
            batch, err = self._results.pop(self._got)
            self._got += 1
            self._dispatch()
            if err is not None:
                raise err
            if batch is None:  # every sample skipped: move to the next one
                continue
            return batch

    next = __next__
