"""Vision transforms (reference
``python/mxnet/gluon/data/vision/transforms.py``: Compose, Cast, ToTensor,
Normalize, RandomResizedCrop, CenterCrop, Resize, RandomFlipLeftRight,
RandomFlipTopBottom, RandomBrightness/Contrast/Saturation/Hue/ColorJitter,
RandomLighting).
"""
from __future__ import annotations

import numpy as np

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from ....ndarray import ndarray as _nd
from ....ndarray.ndarray import NDArray

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "RandomResizedCrop",
           "CenterCrop", "Resize", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomColorJitter", "RandomLighting"]


class Compose(Sequential):
    """reference transforms.py:33."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    """reference transforms.py:79."""

    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference transforms.py:98)."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    """reference transforms.py:130."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = np.asarray(self._mean, dtype="float32")
        std = np.asarray(self._std, dtype="float32")
        if mean.ndim == 1:
            mean = mean.reshape(-1, 1, 1)
        if std.ndim == 1:
            std = std.reshape(-1, 1, 1)
        return (x - _nd.array(mean)) / _nd.array(std)


def _resize(img_np, size, interp="bilinear"):
    import jax
    import jax.numpy as jnp
    h, w = size if isinstance(size, (list, tuple)) else (size, size)
    if img_np.ndim == 2:
        img_np = img_np[:, :, None]
    out = jax.image.resize(jnp.asarray(img_np, jnp.float32),
                           (h, w, img_np.shape[2]), method="linear")
    return np.asarray(out)


class Resize(Block):
    """reference transforms.py:366."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        size = self._size
        if isinstance(size, int):
            if self._keep:
                h, w = img.shape[:2]
                if h < w:
                    size = (size, int(w * size / h))
                else:
                    size = (int(h * size / w), size)
            else:
                size = (size, size)
        elif isinstance(size, (list, tuple)) and len(size) == 2:
            size = (size[1], size[0])  # MXNet Resize takes (w, h)
        return _nd.array(_resize(img, size))


class CenterCrop(Block):
    """reference transforms.py:339."""

    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else \
            (size[1], size[0])

    def forward(self, x):
        img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        h, w = img.shape[:2]
        th, tw = self._size
        if h < th or w < tw:
            img = _resize(img, (max(h, th), max(w, tw)))
            h, w = img.shape[:2]
        y0 = (h - th) // 2
        x0 = (w - tw) // 2
        return _nd.array(img[y0:y0 + th, x0:x0 + tw])


class RandomResizedCrop(Block):
    """reference transforms.py:297."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else \
            (size[1], size[0])
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            aspect = np.exp(np.random.uniform(*log_ratio))
            nw = int(round(np.sqrt(target_area * aspect)))
            nh = int(round(np.sqrt(target_area / aspect)))
            if nw <= w and nh <= h:
                x0 = np.random.randint(0, w - nw + 1)
                y0 = np.random.randint(0, h - nh + 1)
                crop = img[y0:y0 + nh, x0:x0 + nw]
                return _nd.array(_resize(crop, self._size))
        # fallback: center crop
        return CenterCrop((self._size[1], self._size[0])).forward(
            _nd.array(img))


class RandomFlipLeftRight(Block):
    """reference transforms.py:391."""

    def forward(self, x):
        if np.random.rand() < 0.5:
            img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
            return _nd.array(np.ascontiguousarray(img[:, ::-1]))
        return x if isinstance(x, NDArray) else _nd.array(x)


class RandomFlipTopBottom(Block):
    """reference transforms.py:407."""

    def forward(self, x):
        if np.random.rand() < 0.5:
            img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
            return _nd.array(np.ascontiguousarray(img[::-1]))
        return x if isinstance(x, NDArray) else _nd.array(x)


class _RandomJitter(Block):
    def __init__(self, magnitude):
        super().__init__()
        self._m = magnitude

    def _alpha(self):
        return 1.0 + np.random.uniform(-self._m, self._m)


class RandomBrightness(_RandomJitter):
    """reference transforms.py:423."""

    def forward(self, x):
        img = x.asnumpy().astype("float32") if isinstance(x, NDArray) \
            else np.asarray(x, "float32")
        return _nd.array(np.clip(img * self._alpha(), 0, 255))


class RandomContrast(_RandomJitter):
    """reference transforms.py:443."""

    def forward(self, x):
        img = x.asnumpy().astype("float32") if isinstance(x, NDArray) \
            else np.asarray(x, "float32")
        alpha = self._alpha()
        gray = img.mean()
        return _nd.array(np.clip(alpha * img + (1 - alpha) * gray, 0, 255))


class RandomSaturation(_RandomJitter):
    """reference transforms.py:463."""

    def forward(self, x):
        img = x.asnumpy().astype("float32") if isinstance(x, NDArray) \
            else np.asarray(x, "float32")
        alpha = self._alpha()
        gray = img.mean(axis=2, keepdims=True)
        return _nd.array(np.clip(alpha * img + (1 - alpha) * gray, 0, 255))


class RandomColorJitter(Block):
    """reference transforms.py:503."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        order = np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference transforms.py:531)."""

    _eigval = np.array([55.46, 4.794, 1.148])
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]])

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        img = x.asnumpy().astype("float32") if isinstance(x, NDArray) \
            else np.asarray(x, "float32")
        alpha = np.random.normal(0, self._alpha, 3)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return _nd.array(np.clip(img + rgb, 0, 255))
