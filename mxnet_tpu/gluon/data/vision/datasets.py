"""Vision datasets (reference ``python/mxnet/gluon/data/vision/datasets.py``:
MNIST, FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset,
ImageFolderDataset).

No-egress environment: each dataset reads standard local files when present
under ``root``; otherwise raises with instructions — plus a deterministic
``synthetic`` mode used by tests/benchmarks (same shapes/dtypes as the real
data), so the full training pipeline is exercisable offline.
"""
from __future__ import annotations

import gzip
import os
import struct
import warnings

import numpy as np

from .. import dataset
from ....ndarray import ndarray as _nd

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(dataset.Dataset):
    def __init__(self, root, transform):
        super().__init__()
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


def _synthetic(shape, num_classes, n, seed):
    rng = np.random.RandomState(seed)
    data = (rng.rand(n, *shape) * 255).astype(np.uint8)
    label = rng.randint(0, num_classes, n).astype(np.int32)
    return data, label


class MNIST(_DownloadedDataset):
    """reference datasets.py:36. Looks for the standard idx files under
    root; falls back to deterministic synthetic data with a warning."""

    _train_files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")
    _shape = (28, 28, 1)
    _classes = 10
    _synthetic_n = 2048

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None, synthetic=None):
        self._train = train
        self._synthetic = synthetic
        super().__init__(root, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            _, _, dims = struct.unpack(">HBB", f.read(4))
            shape = tuple(struct.unpack(">I", f.read(4))[0]
                          for _ in range(dims))
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        img_path = os.path.join(self._root, files[0])
        lbl_path = os.path.join(self._root, files[1])
        for p in (img_path, lbl_path):
            if not os.path.exists(p) and os.path.exists(p[:-3]):
                p_raw = p[:-3]
        if os.path.exists(img_path) or os.path.exists(img_path[:-3]):
            img = self._read_idx(img_path if os.path.exists(img_path)
                                 else img_path[:-3])
            lbl = self._read_idx(lbl_path if os.path.exists(lbl_path)
                                 else lbl_path[:-3])
            data = img.reshape(img.shape[0], 28, 28, 1)
            label = lbl.astype(np.int32)
        else:
            if self._synthetic is False:
                raise RuntimeError(
                    "MNIST files not found under %s and network egress is "
                    "disabled; place %s there" % (self._root, files))
            warnings.warn("MNIST data not found under %s — using "
                          "deterministic synthetic data" % self._root)
            data, label = _synthetic(self._shape, self._classes,
                                     self._synthetic_n,
                                     seed=42 if self._train else 43)
        self._data = _nd.array(data, dtype=np.uint8)
        self._label = label


class FashionMNIST(MNIST):
    """reference datasets.py:100."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None, synthetic=None):
        super().__init__(root, train, transform, synthetic)


class CIFAR10(_DownloadedDataset):
    """reference datasets.py:127 (binary batches format)."""

    _shape = (32, 32, 3)
    _classes = 10
    _synthetic_n = 2048

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None, synthetic=None):
        self._train = train
        self._synthetic = synthetic
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(
                -1, 3072 + 1)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        if self._train:
            filenames = [os.path.join(self._root,
                                      "data_batch_%d.bin" % (i + 1))
                         for i in range(5)]
        else:
            filenames = [os.path.join(self._root, "test_batch.bin")]
        if all(os.path.exists(f) for f in filenames):
            data, label = zip(*[self._read_batch(f) for f in filenames])
            data = np.concatenate(data)
            label = np.concatenate(label)
        else:
            if self._synthetic is False:
                raise RuntimeError("CIFAR10 binaries not found under %s"
                                   % self._root)
            warnings.warn("CIFAR10 data not found under %s — using "
                          "deterministic synthetic data" % self._root)
            data, label = _synthetic(self._shape, self._classes,
                                     self._synthetic_n,
                                     seed=44 if self._train else 45)
        self._data = _nd.array(data, dtype=np.uint8)
        self._label = label


class CIFAR100(CIFAR10):
    """reference datasets.py:171."""

    _classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None,
                 synthetic=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform, synthetic)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(
                -1, 3072 + 2)
        return data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0 + self._fine_label].astype(np.int32)

    def _get_data(self):
        fname = "train.bin" if self._train else "test.bin"
        path = os.path.join(self._root, fname)
        if os.path.exists(path):
            data, label = self._read_batch(path)
        else:
            if self._synthetic is False:
                raise RuntimeError("CIFAR100 binaries not found under %s"
                                   % self._root)
            warnings.warn("CIFAR100 data not found under %s — using "
                          "deterministic synthetic data" % self._root)
            data, label = _synthetic(self._shape, self._classes,
                                     self._synthetic_n,
                                     seed=46 if self._train else 47)
        self._data = _nd.array(data, dtype=np.uint8)
        self._label = label


class ImageRecordDataset(dataset.RecordFileDataset):
    """reference datasets.py:217."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = super().__getitem__(idx)
        header, img = unpack_img(record)
        if self._transform is not None:
            return self._transform(_nd.array(img), header.label)
        return _nd.array(img), header.label


class ImageFolderDataset(dataset.Dataset):
    """reference datasets.py:247 — folder-per-class layout."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".npy"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                warnings.warn("Ignoring %s, which is not a directory." % path)
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    warnings.warn("Ignoring %s of type %s. Only support %s"
                                  % (filename, ext, ", ".join(self._exts)))
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        fname, label = self.items[idx]
        if fname.endswith(".npy"):
            img = np.load(fname)
        else:
            from PIL import Image
            img = np.asarray(Image.open(fname))
        img = _nd.array(img)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
