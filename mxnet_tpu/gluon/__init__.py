"""Gluon: the imperative/hybrid front-end (reference ``python/mxnet/gluon/``)."""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from . import utils

_LAZY = {
    "trainer": ".trainer",
    "Trainer": (".trainer", "Trainer"),
    "data": ".data",
    "rnn": ".rnn",
    "model_zoo": ".model_zoo",
    "contrib": ".contrib",
}


def __getattr__(name):
    spec = _LAZY.get(name)
    if spec is None:
        raise AttributeError("module 'mxnet_tpu.gluon' has no attribute %r"
                             % name)
    import importlib
    if isinstance(spec, tuple):
        mod = importlib.import_module(spec[0], __name__)
        val = getattr(mod, spec[1])
    else:
        val = importlib.import_module(spec, __name__)
    globals()[name] = val
    return val
