"""Gluon utilities.

Parity surface: reference ``python/mxnet/gluon/utils.py`` —
``split_data``/``split_and_load`` (:31,100 — the data-parallel batch
splitter used with multi-context training) and ``clip_global_norm`` (:131).
"""
from __future__ import annotations

import numpy as _np

from ..context import Context
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch_axis into num_slice slices (reference utils.py:31)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's a multiple of the number of "
            "devices, or set even_split=False." % (
                str(data.shape), num_slice, batch_axis))
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split batch and load each slice to one context (reference
    utils.py:100)."""
    if not isinstance(data, NDArray):
        data = _nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the sum of their 2-norms is <= max_norm (reference
    utils.py:131)."""
    def _norm(array):
        x = array.reshape((-1,))
        return _nd.NDArray((x._data * x._data).sum())

    assert len(arrays) > 0
    ctx = arrays[0].ctx
    total_norm = sum(float(_norm(a).asnumpy()) for a in arrays)
    total_norm = _np.sqrt(total_norm)
    if check_isfinite and not _np.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Kept for API parity; this environment has no egress, so only
    file:// URLs or already-present files work."""
    import os
    fname = path if path and not os.path.isdir(path) else \
        os.path.join(path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise RuntimeError(
        "download(%s) unavailable: network egress is disabled; place the "
        "file at %s manually" % (url, fname))
