"""Gluon losses.

Parity surface: reference ``python/mxnet/gluon/loss.py`` (932 LoC): Loss
base with weight/batch-axis handling, L2/L1, SigmoidBCE, SoftmaxCE, KLDiv,
CTC, Huber, Hinge, SquaredHinge, Logistic, Triplet, PoissonNLL,
CosineEmbedding, SDML.
"""
from __future__ import annotations

import numpy as _np

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss", "CosineEmbeddingLoss", "SDMLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """reference loss.py:39 _apply_weighting."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (float, int)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Base loss (reference loss.py:59)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "{name}(batch_axis={_batch_axis}, w={_weight})".format(
            name=self.__class__.__name__, **self.__dict__)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


def _batch_mean(F, loss, batch_axis):
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    if not axes:
        return loss
    return F.mean(loss, axis=axes)


class L2Loss(Loss):
    """0.5*(pred-label)^2 (reference loss.py:82)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class L1Loss(Loss):
    """|pred-label| (reference loss.py:120)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE with optional from_sigmoid and pos_weight (reference loss.py:157)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu") +
                     F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label +
                         F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE (reference loss.py:240)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            # label < 0 is the ignore convention (the native RecordIO
            # decoder emits -1 for undecodable records): clamp the index
            # for pick, then zero the contribution
            valid = label >= 0
            loss = -F.pick(pred, F.maximum(label, F.zeros_like(label)),
                           axis=self._axis, keepdims=True)
            loss = loss * valid.astype(loss.dtype).reshape(loss.shape)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """KL divergence (reference loss.py:310)."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification (reference loss.py:377; kernel
    `src/operator/nn/ctc_loss.cc` warp-ctc). TPU-native: dynamic-programming
    forward algorithm expressed with lax.scan over the label lattice."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, dim1=0, dim2=1)
        loss = F._ctc_loss(pred, label, pred_lengths, label_lengths)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    """Smooth L1 (reference loss.py:442)."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class HingeLoss(Loss):
    """max(0, margin - pred*label) (reference loss.py:490)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    """reference loss.py:535."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class LogisticLoss(Loss):
    """reference loss.py:580."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if self._label_format not in ["signed", "binary"]:
            raise ValueError("label_format must be signed or binary, got %s"
                             % label_format)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class TripletLoss(Loss):
    """reference loss.py:631."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=tuple(i for i in range(pred.ndim)
                                if i != self._batch_axis))
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    """reference loss.py:678."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            import numpy as np
            stirling_factor = target * F.log(target + 1e-12) - target + \
                0.5 * F.log(2 * target * np.pi + 1e-12)
            stirling_factor = stirling_factor * (target > 1)
            loss = loss + stirling_factor
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    """reference loss.py:741."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos_sim = self._cosine_similarity(F, input1, input2)
        label = label.reshape((-1, 1))
        loss = F.where(label == 1, 1 - cos_sim,
                       F.relu(cos_sim - self._margin))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)

    def _cosine_similarity(self, F, x, y, axis=-1):
        x_norm = F.norm(x, axis=axis).reshape((-1, 1))
        y_norm = F.norm(y, axis=axis).reshape((-1, 1))
        x_dot_y = F.sum(x * y, axis=axis).reshape((-1, 1))
        eps_arr = 1e-12
        return x_dot_y / F.maximum(x_norm * y_norm, eps_arr)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference loss.py:806)."""

    def __init__(self, smoothing_parameter=0.3, weight=1., batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self.kl_loss = KLDivLoss(from_logits=True)
        self.smoothing_parameter = smoothing_parameter

    def _compute_distances(self, x1, x2):
        from .. import ndarray as F
        x1_ = F.expand_dims(x1, axis=1).broadcast_to(
            (x1.shape[0], x2.shape[0], x1.shape[1]))
        x2_ = F.expand_dims(x2, axis=0).broadcast_to(
            (x1.shape[0], x2.shape[0], x2.shape[1]))
        return F.sum(F.square(x1_ - x2_), axis=2)

    def _compute_labels(self, F, batch_size):
        import numpy as np
        gold = np.eye(batch_size)
        labels = gold * (1 - self.smoothing_parameter) + \
            (1 - gold) * self.smoothing_parameter / (batch_size - 1)
        from ..ndarray import array
        return array(labels.astype("float32"))

    def hybrid_forward(self, F, x1, x2):
        batch_size = x1.shape[0]
        labels = self._compute_labels(F, batch_size)
        distances = self._compute_distances(x1, x2)
        log_probabilities = F.log_softmax(-distances, axis=1)
        return self.kl_loss(log_probabilities, labels) * batch_size
