"""Recurrent cells.

Parity surface: reference ``python/mxnet/gluon/rnn/rnn_cell.py``
(RecurrentCell/HybridRecurrentCell, RNNCell, LSTMCell, GRUCell,
SequentialRNNCell, HybridSequentialRNNCell, DropoutCell, ModifierCell,
ZoneoutCell, ResidualCell, BidirectionalCell) — same parameter naming
(i2h/h2h weight+bias) and unroll semantics.
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ...ndarray import ndarray as _nd

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, _nd.NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[axis]
            inputs = [x.squeeze(axis=axis) for x in
                      _split_axis(inputs, inputs.shape[axis], axis)]
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = _nd.stack(*[i for i in inputs], axis=axis)
    return inputs, axis, batch_size


def _split_axis(x, num, axis):
    from ... import ndarray as F
    return F.split(x, num_outputs=num, axis=axis)


def _mask_like(F, data, p):
    return F.Dropout(data.ones_like(), p=p, mode="always")


class RecurrentCell(Block):
    """Base cell (reference rnn_cell.py:77)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells (e.g. ZoneoutCell) the base cell " \
            "cannot be called directly. Call the modifier cell instead."
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info = dict(info)
                shape = info.pop("shape")
                info.pop("__layout__", None)
                info.update(kwargs)
            else:
                shape = (0, 0)
                info = dict(kwargs)
            info = {k: v for k, v in info.items() if k in ("ctx", "dtype")}
            states.append(_nd.zeros(shape, **info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """reference rnn_cell.py:190."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = self.begin_state(batch_size) if begin_state is None \
            else begin_state
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if valid_length is not None:
            from ... import ndarray as F
            stacked = _nd.stack(*outputs, axis=axis)
            stacked = F.SequenceMask(stacked, valid_length,
                                     use_sequence_length=True,
                                     axis=layout.find("T"))
            outputs = stacked if merge_outputs else \
                [o.squeeze(axis=axis) for o in
                 _split_axis(stacked, length, axis)]
            return outputs, states
        if merge_outputs:
            outputs = _nd.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """reference rnn_cell.py:363."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman cell (reference rnn_cell.py:380)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *states):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def __repr__(self):
        s = "{name}({mapping}"
        if hasattr(self, "_activation"):
            s += ", {_activation}"
        s += ")"
        shape = self.i2h_weight.shape
        mapping = "{0} -> {1}".format(shape[1] if shape[1] else None, shape[0])
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        i2h_plus_h2h = i2h + h2h
        output = self._get_activation(F, i2h_plus_h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """reference rnn_cell.py:472 (gate order i,f,c,o matching rnn-inl.h)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)
        self._activation = activation
        self._recurrent_activation = recurrent_activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *states):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=1)
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """reference rnn_cell.py:599 (gate order r,z,n)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *states):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells (reference rnn_cell.py:706)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        return s.format(name=self.__class__.__name__,
                        modstr="\n".join(
                            "({i}): {m}".format(i=i, m=m)
                            for i, m in self._children.items()))

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values())
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args):
        raise NotImplementedError


class HybridSequentialRNNCell(HybridRecurrentCell):
    """reference rnn_cell.py:788."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    """reference rnn_cell.py:884."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, float)
        self._rate = rate
        self._axes = axes

    def __repr__(self):
        return "{name}(rate={_rate}, axes={_axes})".format(
            name=self.__class__.__name__, **self.__dict__)

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Wraps a base cell (reference rnn_cell.py:931)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified twice" \
            % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def __repr__(self):
        return "{name}({base_cell})".format(name=self.__class__.__name__,
                                            base_cell=self.base_cell)


class ZoneoutCell(ModifierCell):
    """reference rnn_cell.py:986."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: _mask_like(F, like, p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = next_output.zeros_like()
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """reference rnn_cell.py:1049."""

    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """reference rnn_cell.py:1089."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def __repr__(self):
        return "{name}(forward={l_cell}, backward={r_cell})".format(
            name=self.__class__.__name__,
            l_cell=self._children["l_cell"],
            r_cell=self._children["r_cell"])

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(),
                                  batch_size=batch_size, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = self.begin_state(batch_size) if begin_state is None \
            else begin_state
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False,
            valid_length=valid_length)
        from ... import ndarray as F
        outputs = [F.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs = _nd.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
