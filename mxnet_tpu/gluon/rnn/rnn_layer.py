"""Fused recurrent layers: RNN / LSTM / GRU.

Parity surface: reference ``python/mxnet/gluon/rnn/rnn_layer.py`` (_RNNLayer
base; parameter naming {l,r}{i}_{i2h,h2h}_{weight,bias} so checkpoints map
1:1; layouts TNC/NTC; bidirectional; multi-layer; begin_state).
Backend: `mxnet_tpu.ops.rnn.rnn_scan_layer` (lax.scan) instead of the
reference's cuDNN fused kernel (`src/operator/rnn-inl.h:414`).
"""
from __future__ import annotations

from ... import initializer as init_mod
from ..block import HybridBlock
from ...ndarray import ndarray as _nd

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        self._mode = mode  # before super(): _alias() runs during Block init
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param("{}{}_i2h_weight".format(j, i),
                                     shape=(ng * nh, ni),
                                     init=i2h_weight_initializer)
                self._register_param("{}{}_h2h_weight".format(j, i),
                                     shape=(ng * nh, nh),
                                     init=h2h_weight_initializer)
                self._register_param("{}{}_i2h_bias".format(j, i),
                                     shape=(ng * nh,),
                                     init=i2h_bias_initializer)
                self._register_param("{}{}_h2h_bias".format(j, i),
                                     shape=(ng * nh,),
                                     init=h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_shape(self, inputs, *states):
        ni = inputs.shape[2] if self._layout == "TNC" else inputs.shape[2]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, "{}{}_i2h_weight".format(j, i)).shape = \
                    (ng * nh, ni)
            ni = nh * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (reference rnn_layer.py begin_state)."""
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            shape = info.pop("shape")
            states.append(_nd.zeros(shape, **{k: v for k, v in info.items()
                                              if k in ("dtype", "ctx")}))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.ctx,
                                      dtype=inputs.dtype)
        if isinstance(states, _nd.NDArray):
            states = [states]
        out, new_states = self._forward_kernel(F, inputs, states, params)
        if self._layout == "NTC":
            out = F.swapaxes(out, dim1=0, dim2=1)
        if skip_states:
            return out
        return out, new_states

    def _forward_kernel(self, F, inputs, states, params):
        """Stack layers/directions over the scan primitive."""
        ns = len(states)
        h_all = states[0]
        c_all = states[1] if ns > 1 else None
        x = inputs
        h_outs, c_outs = [], []
        for i in range(self._num_layers):
            dir_outs = []
            for d, j in enumerate(["l", "r"][:self._dir]):
                idx = i * self._dir + d
                w_ih = params["{}{}_i2h_weight".format(j, i)]
                w_hh = params["{}{}_h2h_weight".format(j, i)]
                b_ih = params["{}{}_i2h_bias".format(j, i)]
                b_hh = params["{}{}_h2h_bias".format(j, i)]
                h0 = h_all[idx]
                if self._mode == "lstm":
                    y, hT, cT = F._rnn_scan_layer(
                        x, w_ih, w_hh, b_ih, b_hh, h0, c_all[idx],
                        mode=self._mode, reverse=(d == 1))
                    c_outs.append(cT)
                else:
                    y, hT = F._rnn_scan_layer(
                        x, w_ih, w_hh, b_ih, b_hh, h0,
                        mode=self._mode, reverse=(d == 1))
                h_outs.append(hT)
                dir_outs.append(y)
            x = dir_outs[0] if self._dir == 1 else \
                F.concat(*dir_outs, dim=2)
            if self._dropout and i < self._num_layers - 1:
                x = F.Dropout(x, p=self._dropout)
        new_states = [F.stack(*h_outs, axis=0)]
        if self._mode == "lstm":
            new_states.append(F.stack(*c_outs, axis=0))
        return x, new_states


class RNN(_RNNLayer):
    """Elman RNN (reference rnn_layer.py:287)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """LSTM (reference rnn_layer.py:388)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", projection_size,
                         **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """GRU (reference rnn_layer.py:499)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
