"""Activation blocks (reference ``python/mxnet/gluon/nn/activations.py``)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "GELU"]

from .basic_layers import Activation  # canonical home; re-exported here


class LeakyReLU(HybridBlock):
    """reference activations.py:79."""

    def __init__(self, alpha, **kwargs):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be >= 0."
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return "{name}({alpha})".format(name=self.__class__.__name__,
                                        alpha=self._alpha)


class PReLU(HybridBlock):
    """reference activations.py:116 — learned slope."""

    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        if alpha_initializer is None:
            alpha_initializer = init_mod.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        # gamma is positional: tensor args must be positional for the op
        # registry to record them on the tape (grads flow to alpha)
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    """reference activations.py:148."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """reference activations.py:176."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    """reference activations.py:195 — x * sigmoid(beta*x)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """reference activations.py:214."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")
