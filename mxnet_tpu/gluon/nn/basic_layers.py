"""Basic Gluon layers.

Parity surface: reference ``python/mxnet/gluon/nn/basic_layers.py``
(Sequential, HybridSequential, Dense, Dropout, BatchNorm, Embedding,
Flatten, InstanceNorm, LayerNorm, Lambda, HybridLambda) — same parameter
names and structural layout so checkpoints map 1:1.
"""
from __future__ import annotations

import numpy as _np

from ... import _tape
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stack of blocks executed sequentially (reference basic_layers.py:29)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(key=key, block=block)
                           for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer '%s' are HybridBlocks. "
                "Consider using HybridSequential for the best performance."
                % self.prefix, stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Hybridizable Sequential (reference basic_layers.py:103)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(key=key, block=block)
                           for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference basic_layers.py:151; op
    `src/operator/nn/fully_connected.cc:258`). Weight shape
    ``(units, in_units)``, lazily inferred when ``in_units=0``."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten \
            else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "{name}({layout}, {act})".format(
            name=self.__class__.__name__,
            act=self.act if self.act else "linear",
            layout="{0} -> {1}".format(shape[1] if shape[1] else None,
                                       shape[0]))


class Activation(HybridBlock):
    """Activation layer (reference basic_layers.py:367)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "{name}({act})".format(name=self.__class__.__name__,
                                      act=self._act_type)


class Dropout(HybridBlock):
    """Dropout (reference basic_layers.py:406)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return "{name}(p = {rate}, axes={axes})".format(
            name=self.__class__.__name__, rate=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with moving-stat aux state (reference
    basic_layers.py:444; op `src/operator/nn/batch_norm-inl.h`). The moving
    mean/var update is functional: written through the aux sink so it works
    identically in eager and compiled (CachedOp) execution."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._momentum = momentum
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if _np.dtype(dtype).name in ("float16", "bfloat16"):
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        train = _tape.is_training() and not self._kwargs["use_global_stats"]
        if train:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **self._kwargs)
            m = self._momentum
            import jax.numpy as jnp
            new_mean = m * running_mean._data + (1 - m) * mean._data.astype(running_mean._data.dtype)
            new_var = m * running_var._data + (1 - m) * var._data.astype(running_var._data.dtype)
            _tape.aux_write(running_mean, new_mean)
            _tape.aux_write(running_var, new_var)
            return out
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels or None,
            content=", ".join("=".join([k, str(v)])
                              for k, v in self._kwargs.items()))


class Embedding(HybridBlock):
    """Embedding lookup (reference basic_layers.py:550; op
    `src/operator/tensor/indexing_op.cc` Embedding)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": sparse_grad}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **{k: v for k, v in self._kwargs.items()
                                         if k in ("input_dim", "output_dim")})

    def __repr__(self):
        return "{name}({input_dim} -> {output_dim}, {dtype})".format(
            name=self.__class__.__name__, **self._kwargs)


class Flatten(HybridBlock):
    """Flatten to (batch, -1) (reference basic_layers.py:618)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class InstanceNorm(HybridBlock):
    """Instance norm (reference basic_layers.py:637)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta,
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels,
            content=", ".join("=".join([k, str(v)])
                              for k, v in self._kwargs.items()))


class LayerNorm(HybridBlock):
    """Layer norm (reference basic_layers.py:712; op
    `src/operator/nn/layer_norm-inl.h`)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis, "center": center,
                        "scale": scale}
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels,
            content=", ".join("=".join([k, str(v)])
                              for k, v in self._kwargs.items()))


class GroupNorm(HybridBlock):
    """Group norm (reference `gluon/nn/basic_layers.py` GroupNorm, MXNet≥1.6)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)

    def __repr__(self):
        return "{name}(groups={g})".format(name=self.__class__.__name__,
                                           g=self._num_groups)


class Lambda(Block):
    """Wrap a function as a Block (reference basic_layers.py:783)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: %r" % function)

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "{name}({function})".format(name=self.__class__.__name__,
                                           function=self._func_name)


class HybridLambda(HybridBlock):
    """Wrap a function as a HybridBlock (reference basic_layers.py:824)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            fname = function
            self._func = lambda F, *args: getattr(F, fname)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: %r" % function)

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return "{name}({function})".format(name=self.__class__.__name__,
                                           function=self._func_name)
