"""Gluon Block / HybridBlock.

Parity surface: reference ``python/mxnet/gluon/block.py`` — ``Block`` (:228,
imperative container with auto-registered children/params),
``HybridBlock`` (:838, `hybridize()` :1039 builds a CachedOp :932 and
replays it :979), parameter save/load, `export`.

TPU-native design: `hybridize()` wraps the block's forward in
``mxnet_tpu.cached_op.CachedOp`` — one ``jax.jit`` trace per input
signature, parameters passed as explicit program inputs so XLA sees a
closed functional program (and gradients flow to parameters through the
single recorded tape node, exactly like the reference records one
``_CachedOp`` node). There is no symbolic tracing language: the eager
NDArray API itself is traceable.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

from ..context import current_context
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]

_naming = threading.local()


class _BlockScope:
    """Name manager for automatic prefixing (reference `gluon/block.py:33`)."""

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def current():
        return getattr(_naming, "scope", None)

    @staticmethod
    def create(prefix, params, hint):
        current = _BlockScope.current()
        if current is None:
            if prefix is None:
                prefix = _namegen(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = _BlockScope.current()
        _naming.scope = self
        return self

    def __exit__(self, *a):
        if self._block._empty_prefix:
            return
        _naming.scope = self._old_scope


_global_counter = {}


def _namegen(hint):
    count = _global_counter.get(hint, 0)
    _global_counter[hint] = count + 1
    return "%s%d" % (hint, count)


def _flatten(args):
    """Flatten nested list/tuple of NDArrays into a flat list + treedef."""
    if isinstance(args, NDArray):
        return [args], int(0)
    if isinstance(args, (list, tuple)):
        flat, fmts = [], []
        for a in args:
            f, fmt = _flatten(a)
            flat.extend(f)
            fmts.append(fmt)
        return flat, fmts
    return [args], None


def _regroup(flat, fmt):
    if fmt is None:
        return flat[0], flat[1:]
    if isinstance(fmt, int):
        return flat[0], flat[1:]
    out = []
    for f in fmt:
        res, flat = _regroup(flat, f)
        out.append(res)
    return out, flat


class Block:
    """Base building block (reference `gluon/block.py:228`)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(str(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Auto-register children and parameters (reference block.py:254)."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError("Changing attribute type for %s from %s to %s"
                                % (name, type(existing), type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed" % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _check_container_with_block(self):
        pass

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All Parameters of this block and children, optionally filtered by
        regex (reference block.py:504)."""
        self._check_container_with_block()
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, hook)
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks, hook)
        return handle

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as init_mod
        if init is None:
            init = init_mod.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def save_parameters(self, filename, deduplicate=False):
        """Structural-name save (reference block.py:428 save_parameters);
        format is a dict-of-arrays file loadable by ``mx.nd.load``."""
        params = self._collect_params_with_prefix()
        from ..ndarray import ndarray as _nd
        arg_dict = {key: val._reduce() for key, val in params.items()}
        _nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..ndarray import ndarray as _nd
        loaded = _nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        # legacy full-name format fallback (ParameterDict.save)
        if loaded and not any("." in k for k in loaded.keys()) and \
                not set(loaded.keys()) & set(params.keys()):
            del loaded
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix)
            return
        if not allow_missing:
            for name in params.keys():
                assert name in loaded, \
                    "Parameter %s is missing in file %s" % (name, filename)
        for name in loaded:
            if name not in params:
                assert ignore_extra, \
                    "Parameter %s loaded from file %s is not present in this " \
                    "block" % (name, filename)
                continue
            from .parameter import load_param_from_array
            load_param_from_array(params[name], loaded[name], ctx)

    save_params = save_parameters
    load_params = load_parameters

    def summary(self, *inputs):
        from ..visualization import block_summary
        return block_summary(self, *inputs)

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError


class _HookHandle:
    _id = 0

    def __init__(self, hooks, hook):
        _HookHandle._id += 1
        self._hooks = hooks
        self._key = _HookHandle._id
        hooks[self._key] = hook

    def detach(self):
        self._hooks.pop(self._key, None)


def _indent(s, num):
    lines = s.split("\n")
    first = lines.pop(0)
    return first + "".join("\n" + " " * num + line for line in lines)


class HybridBlock(Block):
    """Block that can be compiled to one XLA program (reference
    `gluon/block.py:838`). Subclasses implement
    ``hybrid_forward(F, x, *args, **params)``; parameters registered via
    ``self.params.get(...)`` are injected as keyword arguments."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_op = None
        self._cached_params = None

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            if not isinstance(block, Block):
                raise ValueError("children of HybridBlock must be HybridBlock")
        super().register_child(block, name)
        self._clear_cached_op()

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._clear_cached_op()
        for child in self._children.values():
            child.hybridize(active, static_alloc=static_alloc,
                            static_shape=static_shape, **kwargs)

    def _clear_cached_op(self):
        self._cached_op = None
        self._cached_params = None

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from input shapes. Layers with
        lazily-shaped weights override this (the reference resolves it via
        symbolic infer_shape, `gluon/block.py:785 _deferred_infer_shape`)."""
        raise DeferredInitializationError(
            "%s has parameters with unresolved shapes and does not implement "
            "infer_shape" % type(self).__name__)

    def infer_type(self, *args):
        pass

    def _get_ctx(self, args):
        flat, _ = _flatten(list(args))
        for a in flat:
            if isinstance(a, NDArray):
                return a.ctx
        return current_context()

    def _eager_forward(self, *args):
        ctx = self._get_ctx(args)
        params = {}
        try:
            for name, p in self._reg_params.items():
                params[name] = p.data(ctx)
        except DeferredInitializationError:
            self._finish_deferred(args, ctx)
            params = {name: p.data(ctx)
                      for name, p in self._reg_params.items()}
        from .. import ndarray as F
        return self.hybrid_forward(F, *args, **params)

    def _finish_deferred(self, args, ctx):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init:
                p._finish_deferred_init()

    def _symbolic_forward(self, *args):
        """Compose this block into a Symbol graph: parameters become named
        variables, so nested blocks build one DAG (reference
        `gluon/block.py:1128` HybridBlock.forward's symbol branch)."""
        from .. import symbol as sym_ns
        # aux-ness (BatchNorm moving stats etc.) is marked by the op the
        # variable composes into (_sym_op aux slots), not by grad_req —
        # a frozen weight is still an argument
        params = {name: sym_ns.var(p.name)
                  for name, p in self._reg_params.items()}
        return self.hybrid_forward(sym_ns, *args, **params)

    def forward(self, *args):
        from ..symbol.symbol import Symbol as _Sym
        flat, fmt = _flatten(list(args))
        self._in_fmt = fmt
        if any(isinstance(a, _Sym) for a in flat):
            return self._symbolic_forward(*args)
        # remember which flat slots carried tensors (and the values of the
        # ones that didn't) so export() can rebuild the exact call
        self._in_tensor_mask = [isinstance(a, NDArray) for a in flat]
        self._in_const_vals = [None if isinstance(a, NDArray) else a
                               for a in flat]
        if self._active:
            return self._call_cached_op(*args)
        return self._eager_forward(*args)

    # ---- cached-op machinery ---------------------------------------------
    def _build_cache(self, args):
        """reference `gluon/block.py:932 _build_cache`."""
        from ..cached_op import CachedOp
        params = list(self.collect_params().values())
        # filter params that never initialized (e.g. unused)
        self._cached_params = params
        n_in_box = {}

        def fn(*vals):
            n_in = n_in_box["n"]
            inputs, pvals = vals[:n_in], vals[n_in:]
            saved = []
            try:
                for p, v in zip(params, pvals):
                    for i, d in enumerate(p._data):
                        saved.append((p, i, d._data))
                        d._data = v._data
                args_re, _ = _regroup(list(inputs), self._in_fmt)
                if not isinstance(args_re, list):
                    args_re = [args_re]
                out = self._eager_forward(*args_re)
            finally:
                for p, i, old in reversed(saved):
                    p._data[i]._data = old
            flat_out, self._out_fmt = _flatten(out)
            return flat_out if len(flat_out) > 1 else flat_out[0]

        self._cached_fn_meta = n_in_box
        self._cached_op = CachedOp(fn, name=self.name or "CachedOp",
                                   **{k: v for k, v in self._flags.items()
                                      if k in ("static_alloc", "static_shape",
                                               "inline_limit",
                                               "forward_bulk_size",
                                               "backward_bulk_size")})

    def _call_cached_op(self, *args):
        ctx = self._get_ctx(args)
        # make sure all deferred inits are resolved before tracing: run one
        # eager step if needed (reference runs _deferred_infer_shape first)
        try:
            params = list(self.collect_params().values())
            pvals = [p.data(ctx) for p in params if p._grad_req is not None]
        except (DeferredInitializationError, RuntimeError):
            return self._eager_forward(*args)

        flat_args, self._in_fmt = _flatten(list(args))
        if self._cached_op is None:
            self._build_cache(args)
        self._cached_fn_meta["n"] = len(flat_args)
        pvals = [p.data(ctx) for p in self._cached_params]
        out = self._cached_op(*(flat_args + pvals))
        if isinstance(out, list):
            regrouped, _ = _regroup(out, self._out_fmt)
            return regrouped
        return out

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Serialize for deployment (reference `gluon/block.py:1077`):
        traces the block into a Symbol DAG, saving ``path-symbol.json``
        (loadable via ``SymbolBlock.imports`` / ``mx.sym.load``) and
        ``path-%04d.params`` (``arg:``/``aux:``-prefixed binary container,
        the reference's export format). Returns (symbol_file, params_file).

        Call the block on real data once first so the input structure is
        known (same requirement as the reference)."""
        from .. import symbol as sym_ns
        fmt = getattr(self, "_in_fmt", None)
        if fmt is None:
            fmt = int(0)  # never called: assume a single input named 'data'
        flat_n = 1 if not isinstance(fmt, list) else len(fmt)
        mask = getattr(self, "_in_tensor_mask", None) or [True] * flat_n
        consts = getattr(self, "_in_const_vals", None) or [None] * flat_n
        n_tensors = sum(mask)
        names = ["data"] if n_tensors == 1 else \
            ["data%d" % i for i in range(n_tensors)]
        # non-tensor slots (None masks, scalar flags) are replayed with the
        # values from the last forward call, not turned into graph inputs
        slots, it = [], iter(names)
        for is_tensor, const in zip(mask, consts):
            slots.append(sym_ns.var(next(it)) if is_tensor else const)
        args_re, _ = _regroup(slots, fmt)
        if not isinstance(args_re, list):
            args_re = [args_re]
        out = self(*args_re)
        if isinstance(out, (list, tuple)):
            out = sym_ns.Group(list(out))
        symbol_file = "%s-symbol.json" % path
        out.save(symbol_file)
        graph_inputs = set(out.list_inputs())
        aux_names = set(out.list_auxiliary_states())
        from ..ndarray import ndarray as _nd
        arg_dict = {}
        for name, p in self.collect_params().items():
            if name not in graph_inputs:
                continue  # params unused by forward aren't part of the graph
            kind = "aux" if name in aux_names else "arg"
            arg_dict["%s:%s" % (kind, name)] = p._reduce()
        params_file = "%s-%04d.params" % (path, epoch)
        _nd.save(params_file, arg_dict)
        return symbol_file, params_file

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args):
        return super().__call__(*args)


class SymbolBlock(HybridBlock):
    """Run a Symbol graph as a Block (reference `gluon/block.py:1190`).

    The graph's variables (minus the declared inputs) become Parameters, so
    an imported model supports the full Block surface: forward on NDArrays
    (with autograd — ops dispatch through the registry and record on the
    tape), ``hybridize()`` (the evaluator is pure-JAX, so CachedOp jits the
    whole graph to one XLA program), re-export, and fine-tuning."""

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load an exported model: symbol JSON + optional binary params
        (reference `gluon/block.py:1252`)."""
        from .. import symbol as sym_ns
        out = sym_ns.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_ns.var(n) for n in input_names]
        ret = SymbolBlock(out, inputs)
        if param_file is not None:
            from ..ndarray import ndarray as _nd
            from .parameter import load_param_from_array
            loaded = _nd.load(param_file)
            if isinstance(loaded, list):  # zero-name container == no params
                if loaded:
                    raise ValueError(
                        "params file %s has unnamed arrays; SymbolBlock "
                        "needs name->array entries" % param_file)
                loaded = {}
            params = ret.collect_params()
            for key, v in loaded.items():
                name = key.split(":", 1)[1] \
                    if key.startswith(("arg:", "aux:")) else key
                if name not in params._params:
                    raise AssertionError(
                        "Parameter %s in file %s is not a variable of the "
                        "symbol graph" % (name, param_file))
                load_param_from_array(params._params[name], v, ctx)
        return ret

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol.symbol import Symbol as _Sym, Group as _Group
        if isinstance(outputs, (list, tuple)):
            outputs = _Group(list(outputs))
        if isinstance(inputs, _Sym):
            inputs = [inputs]
        self._sb_outputs = outputs
        self._input_names = [i.name for i in inputs]
        for node in outputs._toposort():
            if node._op is not None or node._name in self._input_names:
                continue
            is_aux = bool(node._attr.get("__aux__"))
            p = self.params.get(node._name,
                                grad_req="null" if is_aux else "write",
                                allow_deferred_init=True)
            self._reg_params[node._name] = p

    def infer_shape(self, *args):
        """Resolve parameter shapes from input shapes via the symbol shape
        pass — lets an imports() without a param file be initialized."""
        known = {n: a.shape for n, a in zip(self._input_names, args)}
        from ..symbol.symbol import _infer_shapes
        shapes = _infer_shapes(self._sb_outputs, known)
        for name, p in self._reg_params.items():
            if shapes.get(name) is not None:
                p.shape = tuple(shapes[name])

    def hybrid_forward(self, F, *args, **params):
        if len(args) != len(self._input_names):
            raise ValueError("SymbolBlock expects %d inputs (%s), got %d"
                             % (len(self._input_names), self._input_names,
                                len(args)))
        bindings = dict(zip(self._input_names, args))
        bindings.update(params)
        outs = _eval_symbol_graph(self._sb_outputs, bindings, F)
        return outs if len(outs) > 1 else outs[0]


def _eval_symbol_graph(root, bindings, F):
    """Topologically evaluate a Symbol DAG by dispatching each node through
    the F namespace (nd → registry invoke with tape recording; symbol →
    graph re-composition). The graph-executor analogue for Block use."""
    from ..symbol.symbol import _out_key, _node_arg_values
    values = {}
    for node in root._toposort():
        if node._op is None:
            if node._name not in bindings:
                raise ValueError("unbound variable %r in SymbolBlock"
                                 % node._name)
            values[_out_key(node, 0)] = bindings[node._name]
            continue
        call_args = _node_arg_values(node, values)
        out = getattr(F, node._op.name)(*call_args, **node._kwargs)
        if isinstance(out, (tuple, list)):
            for i, v in enumerate(out):
                values[_out_key(node, i)] = v
        else:
            values[_out_key(node, 0)] = out
    return [values[_out_key(s, i)] for s, i in root._outputs_list()]
