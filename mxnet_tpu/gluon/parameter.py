"""Gluon Parameter / ParameterDict.

Parity surface: reference ``python/mxnet/gluon/parameter.py`` (Parameter with
deferred init, per-context copies, grad_req; ParameterDict with prefix
scoping, get/initialize/save/load). The TPU-native difference: device copies
are ``jax.Array``s and data-parallel replication is usually replaced by a
*sharded* single array (see mxnet_tpu.parallel) — the per-context list API
is kept for MXNet compatibility and single-host multi-device eager use.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from .. import initializer as init_mod
from ..base import MXNetError, dtype_np
from ..context import Context, current_context, cpu
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape is known (reference
    `python/mxnet/gluon/parameter.py:38`)."""


def _shape_complete(shape):
    return shape is not None and all(s > 0 for s in shape)


class Parameter:
    """A trainable parameter: holds one NDArray copy per context.

    reference `python/mxnet/gluon/parameter.py:49` — same lifecycle:
    construct (maybe with unknown dims as 0) → initialize() → (deferred until
    shape known) → data()/grad().
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self._var = None
        self._data = None
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if shape is not None else (
            None if shape is None else tuple(shape))
        if isinstance(shape, int):
            self._shape = (shape,)
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._grad_req = None
        self.grad_req = grad_req
        self._stype = stype
        self._grad_stype = grad_stype
        # set by mxnet_tpu.parallel when the model is sharded over a mesh
        self.sharding = None

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self._shape, self.dtype)

    # ---- grad_req ---------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError("invalid grad_req %r" % req)
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                for d in self._data:
                    d._grad = None
                    d._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(s1 in (0, s2) for s1, s2 in
                         zip(self._shape, tuple(new_shape)))
        if len(self._shape) != len(tuple(new_shape)) or not unknown_ok:
            raise AssertionError(
                "expected shape %s is incompatible with given shape %s for "
                "parameter %s" % (self._shape, tuple(new_shape), self.name))
        self._shape = tuple(new_shape)

    # ---- init -------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """reference `gluon/parameter.py` Parameter.initialize."""
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if not _shape_complete(self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid "
                "shape %s; set allow_deferred_init or complete the shape"
                % (self.name, self._shape))
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        if not _shape_complete(self._shape):
            raise DeferredInitializationError(
                "deferred init of %s failed: shape %s still unknown"
                % (self.name, self._shape))
        if data is None:
            host = _np.zeros(self._shape, dtype=dtype_np(self.dtype))
            host_nd = _nd.array(host, ctx=cpu(), dtype=self.dtype)
            initializer = init if init is not None else default_init
            if isinstance(initializer, str):
                initializer = init_mod.create(initializer)
            initializer(init_mod.InitDesc(self.name), host_nd)
            data = host_nd
        self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        import jax
        import jax.numpy as jnp

        self._ctx_list = list(ctx_list)
        dt = dtype_np(self.dtype)
        # Each context copy must OWN its buffer: device_put between CPU
        # devices (and onto the same TPU chip) is zero-copy, so without the
        # explicit copy all ctx copies would alias one buffer — and the
        # optimizer kernels donate parameter buffers, which would delete
        # every sibling copy on the first update.
        self._data = []
        for c in self._ctx_list:
            val = jnp.array(data._data, copy=True)
            val = jax.device_put(val, c.jax_device)
            if val.dtype != _np.dtype(dt):
                val = val.astype(dt)
            self._data.append(NDArray(val, ctx=c))
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        for d in self._data:
            d.attach_grad(self._grad_req)
        self._grad = [d.grad for d in self._data]

    # ---- accessors --------------------------------------------------------
    def _check_init(self):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "Parameter %s deferred initialization not complete"
                    % self.name)
            raise RuntimeError(
                "Parameter %s has not been initialized. Call .initialize() "
                "first" % self.name)

    def _dev_idx(self, ctx):
        if ctx is None:
            if len(self._data) == 1:
                return 0
            ctx = current_context()
        for i, c in enumerate(self._ctx_list):
            if c == ctx:
                return i
        raise RuntimeError(
            "Parameter %s not initialized on context %s (has %s)"
            % (self.name, ctx, self._ctx_list))

    def data(self, ctx=None):
        self._check_init()
        return self._data[self._dev_idx(ctx)]

    def list_data(self):
        self._check_init()
        return list(self._data)

    def grad(self, ctx=None):
        self._check_init()
        if self._grad is None:
            raise RuntimeError("Parameter %s grad_req='null'" % self.name)
        return self._data[self._dev_idx(ctx)].grad

    def list_grad(self):
        self._check_init()
        if self._grad is None:
            raise RuntimeError("Parameter %s grad_req='null'" % self.name)
        return [d.grad for d in self._data]

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return self._deferred_init[1]
        self._check_init()
        return list(self._ctx_list)

    def zero_grad(self):
        if self._grad is None:
            return
        for d in self._data:
            if d.grad is not None:
                d.grad[:] = 0

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init:
                init, ctx, default_init, _ = self._deferred_init
                self._deferred_init = (init, ctx, default_init,
                                       data if isinstance(data, NDArray)
                                       else _nd.array(data))
                return
            raise RuntimeError("set_data on uninitialized Parameter %s"
                               % self.name)
        for d in self._data:
            val = data._data if isinstance(data, NDArray) else data
            import jax
            import jax.numpy as jnp
            # copy=True: the new buffer must not alias the source — the
            # optimizer kernels donate parameter buffers in place
            val = jnp.array(val, copy=True)
            d._data = jax.device_put(val, d.ctx.jax_device).astype(d.dtype)

    def row_sparse_data(self, row_id):
        # sparse storage is API-complete dense fallback on TPU (SURVEY §2.1)
        return self.data()

    def list_row_sparse_data(self, row_id):
        return self.list_data()

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = self._reduce()
            self._init_impl(data, ctx)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)

    def _reduce(self):
        """Average copies across contexts (reference Parameter._reduce)."""
        self._check_init()
        if len(self._data) == 1:
            return NDArray(self._data[0]._data, ctx=cpu())
        acc = sum(d.asnumpy() for d in self._data) / len(self._data)
        return _nd.array(acc, ctx=cpu(), dtype=self.dtype)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        with_autograd = [d for d in self._data]
        self._data = [NDArray(d._data.astype(dtype_np(dtype)), ctx=c)
                      for d, c in zip(with_autograd, self._ctx_list)]
        if self._grad_req != "null":
            self._init_grad()

    def var(self):
        """Symbol variable for this parameter (Module/Symbol interop)."""
        if self._var is None:
            from ..symbol import var
            self._var = var(self.name, shape=self._shape, dtype=self.dtype,
                            lr_mult=self.lr_mult, wd_mult=self.wd_mult,
                            init=self.init)
        return self._var


class Constant(Parameter):
    """Non-updating parameter holding a constant (reference
    `gluon/parameter.py` Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = _nd.array(value)
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(self_i, _, arr):
                arr[:] = value

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(),
                         differentiable=False)


class ParameterDict:
    """Ordered, prefix-scoped dictionary of Parameters (reference
    `python/mxnet/gluon/parameter.py:558`)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = "\n".join(repr(v) for v in self.values())
        return "%s(\n%s\n)" % (self._prefix or "Parameters", s)

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, key):
        return key in self._params

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Get or create a Parameter named prefix+name."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        # merge partially-known shapes
                        v = tuple(v)
                        if len(v) == len(existing):
                            merged = tuple(a if a != 0 else b
                                           for a, b in zip(existing, v))
                            param._shape = tuple(
                                a if a != 0 else b for a, b in zip(v, existing))
                            continue
                    continue
                setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError("no constant named %s" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("duplicate parameter name %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def list_ctx(self):
        s = []
        for v in self.values():
            for c in v.list_ctx():
                if c not in s:
                    s.append(c)
        return s

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param._reduce()
            if not param.name.startswith(strip_prefix):
                raise ValueError("Prefix %s is to be stripped but parameter "
                                 "%s does not start with it"
                                 % (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        _nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = _nd.load(filename)
        if not isinstance(arg_dict, dict):
            raise ValueError("expected dict-of-arrays file")
        arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise AssertionError(
                        "Parameter %s missing in file %s" % (name, filename))
        for name, v in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise AssertionError(
                        "Parameter %s in file %s is not in ParameterDict"
                        % (name, filename))
                continue
            load_param_from_array(self._params[name], v, ctx)


def load_param_from_array(param, arr, ctx=None):
    """Adopt a loaded array into a Parameter: take its shape, initialize if
    needed, set the data (shared by ParameterDict.load, Block.load_parameters
    and SymbolBlock.imports)."""
    param.shape = arr.shape
    if param._data is None and not param._deferred_init:
        param.initialize(ctx=ctx or [current_context()])
    if param._data is not None or param._deferred_init:
        param.set_data(arr)
        if param._deferred_init:
            param._finish_deferred_init()
