"""gluon.contrib.nn layers.

Parity surface: reference
``python/mxnet/gluon/contrib/nn/basic_layers.py`` — Concurrent :31,
HybridConcurrent :64, Identity :97, SparseEmbedding :118,
SyncBatchNorm :165, PixelShuffle1D/2D/3D :244-:354.

TPU notes: SparseEmbedding's row_sparse gradient is a host-framework
trick for huge tables on CPU parameter servers; here it is the dense
Embedding (XLA gathers are fast, and sharded tables ride the mesh — see
mxnet_tpu.parallel). SyncBatchNorm's cross-device statistics come for
free inside an SPMD step (the batch axis is already global), so it is
BatchNorm with the same extended signature.
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn.basic_layers import (Sequential, HybridSequential, Embedding,
                                BatchNorm)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs (reference :31)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (reference :64)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Identity mapping, useful in Concurrent skip branches
    (reference :97)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """API shell over dense Embedding (reference :118 used
    sparse_grad row_sparse storage; see module docstring)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer, **kwargs)

    def __repr__(self):
        return "SparseEmbedding(%d -> %d)" % (self._input_dim,
                                              self._output_dim)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference :165). Under SPMD the normalizing
    statistics are computed over the global batch inside the compiled
    step, so the base implementation already synchronizes; num_devices/
    ndev and key are accepted for API parity."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=
                         running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        self._ndim = ndim
        if isinstance(factor, int):
            factor = (factor,) * ndim
        self._factors = tuple(int(f) for f in factor)

    def __repr__(self):
        return "%s(factors=%s)" % (type(self).__name__, (self._factors,))


class PixelShuffle1D(_PixelShuffle):
    """(N, C*f, W) -> (N, C, W*f) sub-pixel upscale (reference :244)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def hybrid_forward(self, F, x):
        (f,) = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f, 0))   # (N, C, f, W)
        x = F.transpose(x, axes=(0, 1, 3, 2))       # (N, C, W, f)
        return F.reshape(x, shape=(0, 0, -3))       # (N, C, W*f)


class PixelShuffle2D(_PixelShuffle):
    """(N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2) (reference :292)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        x = F.reshape(x, shape=(0, 0, -3, -3))
        return x


class PixelShuffle3D(_PixelShuffle):
    """(N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3)
    (reference :354)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, 0, -4, f2, f3, 0, 0, 0))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        x = F.reshape(x, shape=(0, 0, -3, -3, -3))
        return x
