"""gluon.contrib (reference ``python/mxnet/gluon/contrib/__init__.py``):
experimental layers, cells, and the Estimator fit API."""
from . import nn
from . import rnn
from . import estimator
