"""Estimator event handlers.

Parity surface: reference
``python/mxnet/gluon/contrib/estimator/event_handler.py`` — the six event
mixins (:52-:80) and the stock handlers: StoppingHandler :82,
MetricHandler :122, ValidationHandler :157, LoggingHandler :223,
CheckpointHandler :358, EarlyStoppingHandler :633.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as np

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class EventHandler:
    pass


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch epochs or max_batch batches (reference :82)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.max_epoch = estimator.max_epoch
        self.max_batch = estimator.max_batch
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch == self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch == self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics at epoch begin, update with batch results
    (reference :122)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = list(metrics or [])
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for metric in self.metrics:
            if _is_loss_metric(metric):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


def _is_loss_metric(metric):
    from ....metric import Loss
    return isinstance(metric, Loss)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every N epochs/batches (reference :157)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Periodic training log lines (reference :223)."""

    LOG_PER_EPOCH = 1
    LOG_PER_BATCH = 2

    def __init__(self, log_interval="epoch", metrics=None, priority=np.inf):
        self.logger = logging.getLogger(__name__)
        self.log_interval = log_interval
        self.metrics = list(metrics or [])
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin: epochs=%s", estimator.max_epoch)

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Train finished in %.3fs: %s",
                         time.time() - self.train_start,
                         _fmt_metrics(self.metrics))

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0

    def epoch_end(self, estimator, *args, **kwargs):
        self.logger.info("[Epoch %d] finished in %.3fs: %s",
                         self.current_epoch,
                         time.time() - self.epoch_start,
                         _fmt_metrics(self.metrics))
        self.current_epoch += 1

    def batch_begin(self, estimator, *args, **kwargs):
        if self.log_interval != "epoch":
            self.batch_start = time.time()

    def batch_end(self, estimator, *args, **kwargs):
        if self.log_interval != "epoch" and \
                self.batch_index % self.log_interval == 0:
            self.logger.info("[Epoch %d][Batch %d] %s",
                             self.current_epoch, self.batch_index,
                             _fmt_metrics(self.metrics))
        self.batch_index += 1


def _fmt_metrics(metrics):
    out = []
    for m in metrics:
        name, val = m.get()
        if isinstance(name, (list, tuple)):
            out.extend("%s: %.4f" % (n, v) for n, v in zip(name, val))
        else:
            out.append("%s: %.4f" % (name, val))
    return ", ".join(out)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save params (+ trainer states) periodically and keep the best model
    by a monitored metric (reference :358)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.saved_checkpoints = []
        self.current_epoch = 0
        self.current_batch = 0
        if mode == "auto" and monitor is not None:
            name = monitor.get()[0]
            mode = "min" if "loss" in str(name).lower() or \
                "error" in str(name).lower() else "max"
        self.mode = mode
        self.best = np.inf if mode == "min" else -np.inf

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        self.current_epoch = 0
        self.current_batch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self._save(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self._save(estimator)

    def _save(self, estimator):
        path = os.path.join(self.model_dir, "%s-epoch%d.params"
                            % (self.model_prefix, self.current_epoch))
        estimator.net.save_parameters(path)
        self.saved_checkpoints.append(path)
        while len(self.saved_checkpoints) > self.max_checkpoints:
            old = self.saved_checkpoints.pop(0)
            if os.path.exists(old):
                os.remove(old)
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            improved = val < self.best if self.mode == "min" \
                else val > self.best
            if improved:
                self.best = val
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, "%s-best.params" % self.model_prefix))


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop training when a monitored metric stops improving
    (reference :633)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        if mode == "auto":
            name = monitor.get()[0]
            mode = "min" if "loss" in str(name).lower() or \
                "error" in str(name).lower() else "max"
        self.mode = mode
        if self.mode == "min":
            self.monitor_op = lambda a, b: np.less(a, b - self.min_delta)
        else:
            self.monitor_op = lambda a, b: np.greater(a, b + self.min_delta)

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.best = self.baseline if self.baseline is not None else \
            (np.inf if self.mode == "min" else -np.inf)

    def epoch_end(self, estimator, *args, **kwargs):
        _, current = self.monitor.get()
        if current is None or (isinstance(current, float) and
                               np.isnan(current)):
            self.current_epoch += 1
            return
        if self.monitor_op(current, self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                estimator.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch:
            logging.getLogger(__name__).info(
                "Epoch %d: early stopping (%s did not improve)",
                self.stopped_epoch, self.monitor.get()[0])
