"""Estimator: the high-level gluon fit API.

Parity surface: reference
``python/mxnet/gluon/contrib/estimator/estimator.py:40`` — Estimator(net,
loss, metrics, trainer, context), fit(train_data, val_data, epochs |
batches, event_handlers), fit_batch/evaluate/evaluate_batch overridable,
default handler wiring (Stopping/Metric/Logging + Validation when
val_data given).

TPU note: the per-batch step keeps the reference's eager structure
(forward under autograd.record -> backward -> trainer.step); hybridize()
the net to get the whole step compiled by XLA.
"""
from __future__ import annotations

import logging

from ... import loss as gluon_loss
from ...trainer import Trainer
from ...data import DataLoader
from ....context import current_context
from .... import autograd
from ....metric import EvalMetric, Loss as LossMetric, Accuracy
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, ValidationHandler,
                            LoggingHandler)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        self.loss = self._check_loss(loss)
        self.train_metrics = self._check_metrics(metrics)
        self.context = self._check_context(context)
        self._initialize(initializer)
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.001})
        self.max_epoch = None
        self.max_batch = None
        self.stop_training = False
        self.val_metrics = [_clone_metric(m) for m in self.train_metrics]
        self.train_loss_metrics = [LossMetric(name="loss")]
        self.val_loss_metrics = [LossMetric(name="validation loss")]
        self.logger = logging.getLogger("Estimator")

    # ---- argument checking (reference :101-:189) --------------------------
    @staticmethod
    def _check_loss(loss):
        if isinstance(loss, gluon_loss.Loss):
            return loss
        raise ValueError("loss must be a gluon Loss instance")

    @staticmethod
    def _check_metrics(metrics):
        if metrics is None:
            return [Accuracy()]
        if isinstance(metrics, EvalMetric):
            return [metrics]
        metrics = list(metrics)
        if not all(isinstance(m, EvalMetric) for m in metrics):
            raise ValueError("metrics must be EvalMetric instances")
        return metrics

    @staticmethod
    def _check_context(context):
        if context is None:
            return [current_context()]
        if isinstance(context, (list, tuple)):
            return list(context)
        return [context]

    def _initialize(self, initializer):
        params = self.net.collect_params()
        uninitialized = any(p._data is None and not p._deferred_init
                            for p in params.values())
        if uninitialized:
            self.net.initialize(init=initializer, ctx=self.context)

    # ---- evaluation (reference :191-:244) ---------------------------------
    def evaluate_batch(self, val_batch, batch_axis=0):
        data, label = val_batch[0], val_batch[1]
        pred = self.net(data)
        loss = self.loss(pred, label)
        return data, label, pred, loss

    def evaluate(self, val_data, batch_axis=0, event_handlers=None):
        for metric in self.val_metrics + self.val_loss_metrics:
            metric.reset()
        for batch in val_data:
            _, label, pred, loss = self.evaluate_batch(batch, batch_axis)
            for metric in self.val_metrics:
                metric.update(label, pred)
            for metric in self.val_loss_metrics:
                metric.update(0, loss)

    # ---- training (reference :246-:358) -----------------------------------
    def fit_batch(self, train_batch, batch_axis=0):
        data, label = train_batch[0], train_batch[1]
        batch_size = data.shape[batch_axis]
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        self.trainer.step(batch_size)
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None, batch_axis=0):
        if not isinstance(train_data, DataLoader):
            raise ValueError(
                "Estimator only supports gluon DataLoader input; wrap your "
                "arrays/DataIter in gluon.data.DataLoader")
        if (not epochs) == (not batches):
            raise ValueError("specify exactly one of epochs or batches")
        self.max_epoch = epochs
        self.max_batch = batches
        self.stop_training = False

        event_handlers = self._prepare_default_handlers(
            val_data, event_handlers)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize_handlers(event_handlers)

        for handler in train_begin:
            handler.train_begin(self)

        while not self.stop_training:
            for handler in epoch_begin:
                handler.epoch_begin(self)
            for batch in train_data:
                for handler in batch_begin:
                    handler.batch_begin(self, batch=batch)
                _, label, pred, loss = self.fit_batch(batch, batch_axis)
                for handler in batch_end:
                    handler.batch_end(self, batch=batch, label=label,
                                      pred=pred, loss=loss)
                if self.stop_training:
                    break
            for handler in epoch_end:
                handler.epoch_end(self)

        for handler in train_end:
            handler.train_end(self)
        return self

    # ---- handler plumbing (reference :360-:447) ---------------------------
    def _prepare_default_handlers(self, val_data, event_handlers):
        event_handlers = list(event_handlers or [])
        added = []
        if not any(isinstance(h, StoppingHandler) for h in event_handlers):
            event_handlers.append(StoppingHandler(self.max_epoch,
                                                  self.max_batch))
        if not any(isinstance(h, MetricHandler) for h in event_handlers):
            event_handlers.append(MetricHandler(
                self.train_metrics + self.train_loss_metrics))
            added.append("MetricHandler")
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler)
                        for h in event_handlers):
            event_handlers.append(ValidationHandler(val_data,
                                                    self.evaluate))
            added.append("ValidationHandler")
        if not any(isinstance(h, LoggingHandler) for h in event_handlers):
            metrics = self.train_metrics + self.train_loss_metrics
            if val_data is not None:
                metrics = metrics + self.val_metrics + self.val_loss_metrics
            event_handlers.append(LoggingHandler(metrics=metrics))
            added.append("LoggingHandler")
        if added:
            self.logger.info("default handlers added: %s",
                             ", ".join(added))
        event_handlers.sort(key=lambda h: getattr(h, "priority", 0))
        return event_handlers

    @staticmethod
    def _categorize_handlers(event_handlers):
        train_begin, epoch_begin, batch_begin = [], [], []
        batch_end, epoch_end, train_end = [], [], []
        for h in event_handlers:
            if isinstance(h, TrainBegin):
                train_begin.append(h)
            if isinstance(h, EpochBegin):
                epoch_begin.append(h)
            if isinstance(h, BatchBegin):
                batch_begin.append(h)
            if isinstance(h, BatchEnd):
                batch_end.append(h)
            if isinstance(h, EpochEnd):
                epoch_end.append(h)
            if isinstance(h, TrainEnd):
                train_end.append(h)
        return (train_begin, epoch_begin, batch_begin, batch_end,
                epoch_end, train_end)


def _clone_metric(metric):
    import copy
    return copy.deepcopy(metric)
