"""Gluon Trainer: applies an Optimizer to a set of Parameters.

Parity surface: reference ``python/mxnet/gluon/trainer.py`` (`Trainer` :27,
`_init_kvstore` :169, `step` :305, `allreduce_grads` :334, `update` :366).
Semantics preserved: step() = allreduce across contexts + optimizer update;
grads are rescaled by 1/batch_size via rescale_grad.
"""
from __future__ import annotations

from .. import optimizer as opt
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        param_list = []
        if isinstance(params, (dict, ParameterDict)):
            for key in sorted(list(params.keys())):
                param_list.append(params[key])
            params = param_list
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore_kind = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of " \
                "contexts, but Parameter %s is initialized on %s while " \
                "previous Parameters are initialized on %s." % (
                    param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "instance of Optimizer instead of str"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        """reference trainer.py:169 — decide kvstore/update placement. On
        TPU there is no server role: the store only aggregates; updates
        always run 'on worker' (SURVEY §3.5 note)."""
        from .. import kvstore as kvs
        if self._kvstore_kind is None:
            self._kvstore = None
        else:
            kind = self._kvstore_kind
            if not isinstance(kind, str):
                self._kvstore = kind
            else:
                if len(self._contexts) <= 1 and not kind.startswith("dist"):
                    self._kvstore = None
                else:
                    self._kvstore = kvs.create(kind)
            if self._kvstore is not None and self._compression_params:
                self._kvstore.set_gradient_compression(self._compression_params)
        self._update_on_kvstore = False
        if self._kvstore is not None:
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.init(i, param.list_data()[0])
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def allreduce_grads(self):
        """Sum gradients across contexts and rebroadcast (reference
        trainer.py:334)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                grads = param.list_grad()
                self._kvstore.push(i, grads)
                # pull the *sum of grads* back into each ctx's grad buffer
                self._kvstore.pull(i, out=grads)

    def step(self, batch_size, ignore_stale_grad=False):
        """Normalize by batch_size, aggregate, update (reference
        trainer.py:305)."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _check_and_rescale_grad(self, scale):
        if self._optimizer.rescale_grad != scale:
            self._optimizer.rescale_grad = scale

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._check_and_rescale_grad(self._scale / batch_size)
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    def save_states(self, fname):
        """reference trainer.py — persist optimizer state."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "wb") as fout:
            fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        for updater in self._updaters:
            updater.set_states(states)
            updater.optimizer = self._updaters[0].optimizer
        self._optimizer = self._updaters[0].optimizer
