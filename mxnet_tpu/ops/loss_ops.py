"""Legacy loss-layer and ROI operators.

Role parity: reference ``src/operator/regression_output.cc``
(LinearRegressionOutput :60, MAERegressionOutput :77,
LogisticRegressionOutput :94 — forward passes predictions through, the
LOSS GRADIENT is injected in backward) and ``src/operator/roi_pooling.cc``.
The loss-gradient semantics are wired with jax.custom_vjp, same idiom as
SoftmaxOutput in nn.py.
"""
from __future__ import annotations

from functools import partial as _partial

import jax
import jax.numpy as jnp

from .registry import register


def _regression_output(transform, grad_fn):
    @_partial(jax.custom_vjp, nondiff_argnums=(2,))
    def run(data, label, grad_scale):
        return transform(data)

    def fwd(data, label, grad_scale):
        out = transform(data)
        return out, (out, label)

    def bwd(grad_scale, res, g):
        out, label = res
        num_output = 1
        for d in out.shape[1:]:
            num_output *= d
        grad = grad_fn(out, label.reshape(out.shape)) * (
            grad_scale / num_output)
        return grad.astype(out.dtype), jnp.zeros(label.shape, out.dtype)

    run.defvjp(fwd, bwd)
    return run


_linear_reg = _regression_output(
    lambda x: x, lambda out, label: out - label)
_mae_reg = _regression_output(
    lambda x: x, lambda out, label: jnp.sign(out - label))
_logistic_reg = _regression_output(
    jax.nn.sigmoid, lambda out, label: out - label)


@register("LinearRegressionOutput", aliases=("linear_regression_output",))
def LinearRegressionOutput(data, label, grad_scale=1.0):
    return _linear_reg(data, label, float(grad_scale))


@register("MAERegressionOutput", aliases=("mae_regression_output",))
def MAERegressionOutput(data, label, grad_scale=1.0):
    return _mae_reg(data, label, float(grad_scale))


@register("LogisticRegressionOutput", aliases=("logistic_regression_output",))
def LogisticRegressionOutput(data, label, grad_scale=1.0):
    return _logistic_reg(data, label, float(grad_scale))


@register("IdentityAttachKLSparseReg")
def IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9):
    """Identity forward; the KL sparseness regularizer gradient the
    reference attaches (identity_attach_KL_sparse_reg.cc) is a no-op in
    inference and subsumed by explicit loss terms in training."""
    return data


@register("ROIPooling")
def ROIPooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Max-pool regions of interest to a fixed size (reference
    roi_pooling.cc). rois: (R, 5) rows [batch_idx, x1, y1, x2, y2]."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    H, W = data.shape[2], data.shape[3]

    def pool_one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[b]  # (C, H, W)

        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def cell(iy, ix):
            hstart = y1 + (iy * rh) // ph
            hend = y1 + ((iy + 1) * rh + ph - 1) // ph
            wstart = x1 + (ix * rw) // pw
            wend = x1 + ((ix + 1) * rw + pw - 1) // pw
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                    (xs[None, :] >= wstart) & (xs[None, :] < wend))
            masked = jnp.where(mask[None], img,
                               jnp.full_like(img, -jnp.inf))
            val = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(val), val, 0.0)

        grid = jnp.stack([jnp.stack([cell(iy, ix) for ix in range(pw)],
                                    axis=-1) for iy in range(ph)], axis=-2)
        return grid  # (C, ph, pw)

    return jax.vmap(pool_one)(rois)
