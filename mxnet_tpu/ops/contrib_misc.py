"""Miscellaneous contrib operators.

Role parity: reference ``src/operator/contrib/quadratic_op.cc`` (the
tutorial op), ``contrib/index_copy.cc``, ``contrib/index_array.cc``,
``contrib/optimizer_op.cc`` (group_adagrad_update), and
``contrib/hawkes_ll.cc`` (univariate Hawkes process log-likelihood with
exponential kernel — here a ``lax.scan`` over the event sequence instead
of the reference's per-thread CUDA loop).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, register_alias

__all__ = ["quadratic", "index_copy", "index_array",
           "group_adagrad_update", "hawkesll"]


@register("_contrib_quadratic", aliases=("quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """f(x) = a*x^2 + b*x + c (reference contrib/quadratic_op.cc — MXNet's
    custom-op tutorial operator)."""
    return a * data * data + b * data + c


@register("_contrib_index_copy", aliases=("index_copy",))
def index_copy(old_tensor, index_vector, new_tensor):
    """Copy rows of ``new_tensor`` into ``old_tensor`` at ``index_vector``
    positions (reference contrib/index_copy.cc)."""
    return old_tensor.at[index_vector.astype(jnp.int32)].set(new_tensor)


@register("_contrib_index_array", aliases=("index_array",),
          differentiable=False)
def index_array(data, axes=None):
    """Per-element coordinate array: output shape ``data.shape + (len(axes)
    or ndim,)`` of int64 indices (reference contrib/index_array.cc).

    Documented deviation: the reference always emits int64. Here the
    element type follows jax_enable_x64 — int64 when x64 is on (this
    framework's default), int32 otherwise (e.g. inside the Pallas/Mosaic
    paths, which have no 64-bit types). Coordinates are bounded by array
    dims, so int32 is lossless for any shape XLA can compile."""
    nd = data.ndim
    sel = tuple(range(nd)) if axes is None else tuple(int(a) for a in axes)
    coords = [lax.broadcasted_iota(jnp.int64, data.shape, ax) for ax in sel]
    return jnp.stack(coords, axis=-1)


@register("_contrib_group_adagrad_update",
          aliases=("group_adagrad_update",), n_out=2,
          differentiable=False)
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Row-wise (grouped) AdaGrad (reference contrib/optimizer_op.cc:63):
    history += mean(grad^2, axis=1, keepdims=True);
    weight -= lr * grad / sqrt(history + eps)."""
    g = grad * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    axes = tuple(range(1, g.ndim))
    hist = history + jnp.mean(jnp.square(g), axis=axes, keepdims=True)
    w = weight - lr * g / jnp.sqrt(hist + epsilon)
    return w, hist


@register("_contrib_hawkesll", aliases=("hawkesll",), n_out=2)
def hawkesll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Univariate multi-mark Hawkes log-likelihood (reference
    contrib/hawkes_ll.cc): exponential kernel
    lambda_k*(t) = lda_k + alpha_k * beta_k * s_k(t), ragged (N, T)
    event sequences scanned with lax.scan.

    Returns (loglik (N,), s_k(max_time) (N, K)).
    """
    K = lda.shape[-1]

    def one(lda_n, s0, lag_n, mark_n, vl, T):
        Tn = lag_n.shape[0]

        def step(carry, inp):
            s, t, ll, comp = carry
            j, lag, mark = inp
            valid = (j < vl)
            dec = jnp.exp(-beta * lag)
            s2 = jnp.where(valid, s * dec, s)
            t2 = jnp.where(valid, t + lag, t)
            lam = lda_n + alpha * beta * s2
            onehot = jax.nn.one_hot(mark, K, dtype=s.dtype)
            ll2 = ll + jnp.where(
                valid, jnp.log(jnp.maximum((lam * onehot).sum(), 1e-30)),
                0.0)
            # compensator contribution of this event on (t_j, T]
            comp2 = comp + jnp.where(
                valid, onehot * alpha * (1.0 - jnp.exp(-beta * (T - t2))),
                0.0)
            s3 = jnp.where(valid, s2 + onehot, s2)
            return (s3, t2, ll2, comp2), None

        init = (s0, jnp.zeros((), lda_n.dtype), jnp.zeros((), lda_n.dtype),
                jnp.zeros((K,), lda_n.dtype))
        (s, t_last, ll, comp), _ = lax.scan(
            step, init,
            (jnp.arange(Tn, dtype=jnp.int32), lag_n,
             mark_n.astype(jnp.int32)))
        # initial-state compensator + background rate over (0, T]
        comp_total = (lda_n * T).sum() + comp.sum() + \
            (alpha * s0 * (1.0 - jnp.exp(-beta * T))).sum()
        # decay memory out to T for the returned state
        s_T = s * jnp.exp(-beta * jnp.maximum(T - t_last, 0.0))
        return ll - comp_total, s_T

    ll, s_out = jax.vmap(one)(lda, state, lags, marks,
                              valid_length.astype(jnp.int32), max_time)
    return ll, s_out


@register("_contrib_moe_ffn", aliases=("moe_ffn",), n_out=2)
def moe_ffn_op(data, gate_w, w1, w2, capacity_factor=1.25):
    """Switch-style top-1 MoE FFN (beyond-reference; parallel/moe.py holds
    the math + the expert-parallel ``moe_ffn_sharded`` variant). Returns
    (output, load-balancing aux loss)."""
    from ..parallel.moe import moe_ffn as _impl
    return _impl(data, gate_w, w1, w2,
                 capacity_factor=float(capacity_factor))


# SparseEmbedding: same math as Embedding; the row-sparse gradient storage
# optimization is a GPU-memory concern the TPU build handles densely
# (SURVEY §5.9 sanctions the dense fallback; reference
# src/operator/tensor/indexing_op.cc _contrib_SparseEmbedding).
register_alias("Embedding", "_contrib_SparseEmbedding", "SparseEmbedding")
