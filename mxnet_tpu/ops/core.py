"""Core tensor operators: elemwise, broadcast, reduce, shape, indexing.

Role parity: reference ``src/operator/tensor/`` (~35K LoC of CPU+CUDA
kernels: elemwise_binary_op*, broadcast_reduce_op*, matrix_op, indexing_op,
init_op, ordering_op). TPU-native: each op is a one-liner lowering to
jax.numpy / lax — XLA supplies kernels, fusion, and layout; gradients come
from the tape + jax.vjp, so no FGradient registrations.

MXNet op-name parity is kept via aliases (broadcast_add == add, etc. —
in MXNet these are distinct registrations, e.g.
`src/operator/tensor/elemwise_binary_broadcast_op_basic.cc`).
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import dtype_np
from .registry import register

# ---------------------------------------------------------------- arithmetic


@register("add", aliases=("broadcast_add", "broadcast_plus", "elemwise_add",
                          "_plus", "_add"))
def add(lhs, rhs):
    return jnp.add(lhs, rhs)


@register("subtract", aliases=("broadcast_sub", "broadcast_minus",
                               "elemwise_sub", "_sub", "_minus"))
def subtract(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@register("multiply", aliases=("broadcast_mul", "elemwise_mul", "_mul"))
def multiply(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@register("divide", aliases=("broadcast_div", "elemwise_div", "_div"))
def divide(lhs, rhs):
    return jnp.divide(lhs, rhs)


@register("mod", aliases=("broadcast_mod",))
def mod(lhs, rhs):
    return jnp.mod(lhs, rhs)


@register("fmod")
def fmod(lhs, rhs):
    """C-style truncated modulo (numpy fmod semantics; the reference's
    _npi_fmod, `src/operator/numpy/np_elemwise_broadcast_op.cc`)."""
    return jnp.fmod(lhs, rhs)


@register("power", aliases=("broadcast_power", "_power"))
def power(lhs, rhs):
    return jnp.power(lhs, rhs)


@register("maximum", aliases=("broadcast_maximum", "_maximum"))
def maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register("minimum", aliases=("broadcast_minimum", "_minimum"))
def minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


@register("hypot", aliases=("broadcast_hypot",))
def hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


@register("negative")
def negative(x):
    return jnp.negative(x)


@register("reciprocal")
def reciprocal(x):
    return jnp.reciprocal(x)


@register("abs")
def abs(x):  # noqa: A001 - MXNet op name
    return jnp.abs(x)


@register("sign")
def sign(x):
    return jnp.sign(x)


@register("round")
def round(x):  # noqa: A001
    return jnp.round(x)


@register("rint")
def rint(x):
    return jnp.rint(x)


@register("ceil")
def ceil(x):
    return jnp.ceil(x)


@register("floor")
def floor(x):
    return jnp.floor(x)


@register("trunc")
def trunc(x):
    return jnp.trunc(x)


@register("fix")
def fix(x):
    return jnp.fix(x)


@register("square")
def square(x):
    return jnp.square(x)


@register("sqrt")
def sqrt(x):
    return jnp.sqrt(x)


@register("rsqrt")
def rsqrt(x):
    return lax.rsqrt(x)


@register("cbrt")
def cbrt(x):
    return jnp.cbrt(x)


@register("rcbrt")
def rcbrt(x):
    return 1.0 / jnp.cbrt(x)


@register("exp")
def exp(x):
    return jnp.exp(x)


@register("log")
def log(x):
    return jnp.log(x)


@register("log10")
def log10(x):
    return jnp.log10(x)


@register("log2")
def log2(x):
    return jnp.log2(x)


@register("log1p")
def log1p(x):
    return jnp.log1p(x)


@register("expm1")
def expm1(x):
    return jnp.expm1(x)


@register("gamma")
def gamma(x):
    return jnp.exp(jax.scipy.special.gammaln(x))


@register("gammaln")
def gammaln(x):
    return jax.scipy.special.gammaln(x)


@register("erf")
def erf(x):
    return jax.scipy.special.erf(x)


@register("erfinv")
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@register("digamma")
def digamma(x):
    return jax.scipy.special.digamma(x)


# trig
for _name, _fn in [("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
                   ("arcsin", jnp.arcsin), ("arccos", jnp.arccos),
                   ("arctan", jnp.arctan), ("sinh", jnp.sinh),
                   ("cosh", jnp.cosh), ("tanh", jnp.tanh),
                   ("arcsinh", jnp.arcsinh), ("arccosh", jnp.arccosh),
                   ("arctanh", jnp.arctanh)]:
    register(_name)(lambda x, _f=_fn: _f(x))


@register("degrees")
def degrees(x):
    return jnp.degrees(x)


@register("radians")
def radians(x):
    return jnp.radians(x)


# scalar variants (MXNet registers _plus_scalar etc; our binary ops accept
# scalars natively, but keep the names for generated-code parity)
@register("_plus_scalar")
def _plus_scalar(data, scalar=0.0):
    return data + scalar


@register("_minus_scalar")
def _minus_scalar(data, scalar=0.0):
    return data - scalar


@register("_rminus_scalar")
def _rminus_scalar(data, scalar=0.0):
    return scalar - data


@register("_mul_scalar")
def _mul_scalar(data, scalar=1.0):
    return data * scalar


@register("_div_scalar")
def _div_scalar(data, scalar=1.0):
    return data / scalar


@register("_rdiv_scalar")
def _rdiv_scalar(data, scalar=1.0):
    return scalar / data


@register("_power_scalar")
def _power_scalar(data, scalar=1.0):
    return data ** scalar


@register("_rpower_scalar")
def _rpower_scalar(data, scalar=1.0):
    return scalar ** data


# ------------------------------------------------------------- comparisons


@register("equal", aliases=("broadcast_equal", "_equal"))
def equal(lhs, rhs):
    return (jnp.equal(lhs, rhs)).astype(_res_dtype(lhs, rhs))


def _res_dtype(lhs, rhs):
    d = getattr(lhs, "dtype", None) or getattr(rhs, "dtype", None)
    return d if d is not None and jnp.issubdtype(d, jnp.floating) else jnp.float32


@register("not_equal", aliases=("broadcast_not_equal", "_not_equal"))
def not_equal(lhs, rhs):
    return (jnp.not_equal(lhs, rhs)).astype(_res_dtype(lhs, rhs))


@register("greater", aliases=("broadcast_greater", "_greater"))
def greater(lhs, rhs):
    return (jnp.greater(lhs, rhs)).astype(_res_dtype(lhs, rhs))


@register("greater_equal", aliases=("broadcast_greater_equal", "_greater_equal"))
def greater_equal(lhs, rhs):
    return (jnp.greater_equal(lhs, rhs)).astype(_res_dtype(lhs, rhs))


@register("lesser", aliases=("broadcast_lesser", "_lesser"))
def lesser(lhs, rhs):
    return (jnp.less(lhs, rhs)).astype(_res_dtype(lhs, rhs))


@register("lesser_equal", aliases=("broadcast_lesser_equal", "_lesser_equal"))
def lesser_equal(lhs, rhs):
    return (jnp.less_equal(lhs, rhs)).astype(_res_dtype(lhs, rhs))


@register("logical_and", aliases=("broadcast_logical_and",))
def logical_and(lhs, rhs):
    return jnp.logical_and(lhs, rhs).astype(jnp.float32)


@register("logical_or", aliases=("broadcast_logical_or",))
def logical_or(lhs, rhs):
    return jnp.logical_or(lhs, rhs).astype(jnp.float32)


@register("logical_xor", aliases=("broadcast_logical_xor",))
def logical_xor(lhs, rhs):
    return jnp.logical_xor(lhs, rhs).astype(jnp.float32)


@register("logical_not")
def logical_not(x):
    return jnp.logical_not(x).astype(jnp.float32)


@register("isnan")
def isnan(x):
    return jnp.isnan(x).astype(jnp.float32)


@register("isinf")
def isinf(x):
    return jnp.isinf(x).astype(jnp.float32)


@register("isfinite")
def isfinite(x):
    return jnp.isfinite(x).astype(jnp.float32)


@register("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool) if hasattr(condition, "astype")
                     else condition, x, y)


# ---------------------------------------------------------------- reductions


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


@register("sum", aliases=("sum_axis",))
def sum(data, axis=None, keepdims=False, exclude=False):  # noqa: A001
    axis = _excl(_norm_axis(axis), data.ndim, exclude)
    return jnp.sum(data, axis=axis, keepdims=keepdims)


def _excl(axis, ndim, exclude):
    if not exclude or axis is None:
        return axis
    axes = (axis,) if isinstance(axis, int) else axis
    return tuple(i for i in range(ndim) if i not in axes)


@register("mean")
def mean(data, axis=None, keepdims=False, exclude=False):
    axis = _excl(_norm_axis(axis), data.ndim, exclude)
    return jnp.mean(data, axis=axis, keepdims=keepdims)


@register("prod")
def prod(data, axis=None, keepdims=False, exclude=False):
    axis = _excl(_norm_axis(axis), data.ndim, exclude)
    return jnp.prod(data, axis=axis, keepdims=keepdims)


@register("nansum")
def nansum(data, axis=None, keepdims=False):
    return jnp.nansum(data, axis=_norm_axis(axis), keepdims=keepdims)


@register("nanprod")
def nanprod(data, axis=None, keepdims=False):
    return jnp.nanprod(data, axis=_norm_axis(axis), keepdims=keepdims)


@register("max", aliases=("max_axis",))
def max(data, axis=None, keepdims=False, exclude=False):  # noqa: A001
    axis = _excl(_norm_axis(axis), data.ndim, exclude)
    return jnp.max(data, axis=axis, keepdims=keepdims)


@register("min", aliases=("min_axis",))
def min(data, axis=None, keepdims=False, exclude=False):  # noqa: A001
    axis = _excl(_norm_axis(axis), data.ndim, exclude)
    return jnp.min(data, axis=axis, keepdims=keepdims)


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):  # noqa: A002
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=_norm_axis(axis), keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=_norm_axis(axis),
                            keepdims=keepdims))


@register("argmax")
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims).astype(jnp.float32)
    return out


@register("argmin")
def argmin(data, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argsort")
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(dtype_np(dtype))


@register("sort")
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("topk")
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    if is_ascend:
        data_for = -data
    else:
        data_for = data
    if axis != -1 and axis != data.ndim - 1:
        moved = jnp.moveaxis(data_for, axis, -1)
    else:
        moved = data_for
    vals, idx = lax.top_k(moved, k)
    if is_ascend:
        vals = -vals
    if axis != -1 and axis != data.ndim - 1:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx.astype(dtype_np(dtype))
    if ret_typ == "mask":
        # 0/1 mask over the INPUT shape marking top-k positions
        # (reference ordering_op-inl.h kReturnMask)
        ax = axis if axis >= 0 else data.ndim + axis
        onehot = jax.nn.one_hot(jnp.moveaxis(idx, ax, -1),
                                data.shape[ax], dtype=data.dtype)
        mask = onehot.sum(axis=-2)          # merge the k picks
        return jnp.moveaxis(mask, -1, ax)
    return idx.astype(dtype_np(dtype))


@register("cumsum")
def cumsum(data, axis=None, dtype=None):
    out = jnp.cumsum(data, axis=axis)
    return out.astype(dtype_np(dtype)) if dtype else out


# ------------------------------------------------------------- shape manip


@register("reshape", aliases=("Reshape",))
def reshape(data, shape=None, reverse=False):
    shape = _mx_reshape(tuple(data.shape), tuple(shape), reverse)
    return jnp.reshape(data, shape)


def _mx_reshape(src, spec, reverse=False):
    """MXNet reshape spec: 0 copy dim, -1 infer, -2 copy rest, -3 merge two,
    -4 split (reference `src/operator/tensor/matrix_op-inl.h` ReshapeShape)."""
    if reverse:
        src = src[::-1]
        spec = spec[::-1]
    out, i = [], 0
    spec = list(spec)
    j = 0
    while j < len(spec):
        s = spec[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            d1, d2 = spec[j + 1], spec[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(int(s)); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return tuple(out)


@register("transpose")
def transpose(data, axes=None):
    return jnp.transpose(data, axes=axes)


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("Flatten", aliases=("flatten",))
def Flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("broadcast_to")
def broadcast_to(data, shape=None):
    shape = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    shape = list(data.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(data, tuple(shape))


@register("concat", aliases=("Concat",))
def concat(*data, dim=1, num_args=None):
    return jnp.concatenate(data, axis=dim)


@register("stack")
def stack(*data, axis=0, num_args=None):
    return jnp.stack(data, axis=axis)


@register("split", aliases=("SliceChannel",), n_out=0)
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("slice", aliases=("crop",))
def slice(data, begin=(), end=(), step=()):  # noqa: A001
    import builtins
    step = step or [None] * len(begin)
    idx = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    import builtins
    idx = [builtins.slice(None)] * data.ndim
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, axes=()):
    import builtins
    idx = [builtins.slice(None)] * data.ndim
    axes = axes or builtins.range(data.ndim)
    for a in axes:
        idx[a] = builtins.slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("tile")
def tile(data, reps=()):
    return jnp.tile(data, reps)


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("pad", aliases=("Pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


@register("flip", aliases=("reverse",))
def flip(data, axis=()):
    return jnp.flip(data, axis=axis)


@register("roll")
def roll(data, shift=0, axis=None):
    return jnp.roll(data, shift, axis=axis)


@register("depth_to_space")
def depth_to_space(data, block_size=1):
    b, c, h, w = data.shape
    bs = block_size
    x = jnp.reshape(data, (b, bs, bs, c // (bs * bs), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (b, c // (bs * bs), h * bs, w * bs))


@register("space_to_depth")
def space_to_depth(data, block_size=1):
    b, c, h, w = data.shape
    bs = block_size
    x = jnp.reshape(data, (b, c, h // bs, bs, w // bs, bs))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (b, c * bs * bs, h // bs, w // bs))


# ---------------------------------------------------------------- indexing


@register("_index", differentiable=True)
def _index(data, key=None):
    return data[key]


@register("take")
def take(a, indices, axis=0, mode="clip"):
    return jnp.take(a, indices.astype(jnp.int32), axis=axis,
                    mode="clip" if mode == "clip" else "wrap")


@register("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.expand_dims(index.astype(jnp.int32), axis=axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, shape=None):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[idx].set(data)


@register("one_hot")
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype_np(dtype))
    return oh * (on_value - off_value) + off_value


@register("SequenceMask", aliases=("sequence_mask",))
def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    bshape = [1] * data.ndim
    bshape[axis] = maxlen
    steps = steps.reshape(bshape)
    batch_axis = 1 if axis == 0 else 0
    lshape = [1] * data.ndim
    lshape[batch_axis] = data.shape[batch_axis]
    mask = steps < sequence_length.reshape(lshape)
    return jnp.where(mask, data, value)


@register("SequenceLast")
def SequenceLast(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        import builtins
        idx = [builtins.slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    return jnp.take_along_axis(
        data, last.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=axis
    ).squeeze(axis)


@register("SequenceReverse")
def SequenceReverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    T = data.shape[axis]
    steps = jnp.arange(T)
    sl = sequence_length.astype(jnp.int32)
    rev = jnp.where(steps[None, :] < sl[:, None], sl[:, None] - 1 - steps[None, :],
                    steps[None, :])  # (B, T)
    rev = jnp.swapaxes(rev, 0, 1)  # (T, B)
    rev = rev.reshape((T,) + rev.shape[1:2] + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, rev, axis=0)


# ---------------------------------------------------------------- init-like


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("full_like")
def full_like(data, fill_value=0.0):
    return jnp.full_like(data, fill_value)


@register("shape_array", differentiable=False)
def shape_array(data):
    return jnp.asarray(_np.asarray(data.shape), dtype=jnp.int64)


@register("size_array", differentiable=False)
def size_array(data):
    return jnp.asarray([int(_np.prod(data.shape))], dtype=jnp.int64)


@register("cast", aliases=("Cast",))
def cast(data, dtype="float32"):
    return data.astype(dtype_np(dtype))


@register("amp_cast")
def amp_cast(data, dtype="float32"):
    return data.astype(dtype_np(dtype))


@register("amp_multicast", n_out=0)
def amp_multicast(*data, num_outputs=1, cast_narrow=False):
    dtypes = [d.dtype for d in data]
    target = jnp.result_type(*dtypes) if not cast_narrow else dtypes[0]
    return tuple(d.astype(target) for d in data)


@register("identity", aliases=("_copy", "BlockGrad_identity"))
def identity(data):
    return data


@register("stop_gradient", aliases=("BlockGrad",))
def stop_gradient(data):
    return lax.stop_gradient(data)


@register("make_loss", aliases=("MakeLoss",))
def make_loss(data, grad_scale=1.0, normalization="null", valid_thresh=0.0):
    return data * grad_scale if grad_scale != 1.0 else data


@register("clip")
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


# ---------------------------------------------------------------- linalg


@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([-1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("matmul")
def matmul(lhs, rhs):
    return jnp.matmul(lhs, rhs)


@register("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-3):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-3):
    return linalg_gemm2.fn(A, B, transpose_a, transpose_b, alpha) + beta * C


@register("linalg_potrf")
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    if transpose:
        return alpha * jnp.matmul(jnp.swapaxes(A, -1, -2), A)
    return alpha * jnp.matmul(A, jnp.swapaxes(A, -1, -2))


@register("linalg_trsm")
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    import jax.scipy.linalg as jsl
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    if rightside:
        x = jsl.solve_triangular(jnp.swapaxes(a, -1, -2),
                                 jnp.swapaxes(alpha * B, -1, -2),
                                 lower=not lower)
        return jnp.swapaxes(x, -1, -2)
    return jsl.solve_triangular(a, alpha * B, lower=lower)


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag")
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            (-1,) + out.shape[1:])
    return out


@register("diag")
def diag(data, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("embedding", aliases=("Embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)
