"""Autoregressive-generation operators: seeded sampling + KV-cache writes.

Not in MXNet 1.6 (generation there was a python loop over ``argmax`` /
``random.multinomial`` calls, e.g. ``example/gluon/word_language_model``);
exposed here as first-class ops because the serving decode step compiles
them INTO the fused per-iteration XLA program (``serving/generation``).

Design rules:

- **Explicit PRNG keys.** Every stochastic sampler takes its key as an
  argument (a raw ``(2,)`` uint32 jax key, or an NDArray wrapping one) —
  never the ambient stateful stream. Same key + same logits => same token,
  eagerly and under jit, across processes. That is what makes generation
  replayable and the determinism regression test possible.
- **Pure functions over logits.** No in-place mutation; the cache-write
  ops return the updated buffer (XLA turns the copy into an in-place
  ``dynamic-update-slice`` when the input buffer is dead — inside the
  jitted decode step it always is).
- **Static hyper-parameters.** ``k`` (top-k) and axis arguments are python
  ints baked into the trace; per-slot *temperature* is a traced array so
  one compiled decode step serves greedy and sampling requests mixed in
  the same batch (temperature 0 == greedy, selected branchlessly).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["sample_greedy", "sample_temperature", "sample_top_k",
           "generation_sample", "kv_cache_update", "arena_update",
           "arena_slice"]

_NEG_INF = -1e9  # large-negative fill that stays finite in fp16/bf16


def _as_key(key):
    """Accept a raw (2,) uint32 key array (possibly traced)."""
    return jnp.asarray(key, dtype=jnp.uint32)


@register("_contrib_sample_greedy", aliases=("sample_greedy",),
          differentiable=False)
def sample_greedy(logits):
    """Argmax over the last axis -> int32 token ids ``logits.shape[:-1]``."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@register("_contrib_sample_temperature", aliases=("sample_temperature",),
          differentiable=False)
def sample_temperature(logits, key, temperature=1.0):
    """Categorical sample from ``softmax(logits / temperature)``.

    ``temperature`` may be a scalar or a per-row array ``(B,)`` broadcast
    over ``logits (B, V)``. ``temperature <= 0`` rows degrade to greedy
    (selected with ``where``, so the op stays branchless under jit).
    """
    key = _as_key(key)
    temp = jnp.asarray(temperature, dtype=logits.dtype)
    cold = temp <= 0.0                      # scalar or (B,)
    if temp.ndim == 1:
        temp = temp[:, None]                # broadcast over vocab
    safe = jnp.maximum(temp, jnp.asarray(1e-6, logits.dtype))
    sampled = jax.random.categorical(key, logits / safe, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(cold, greedy, sampled).astype(jnp.int32)


def _top_k_filter(logits, k):
    """Keep the k largest logits per row, fill the rest with -inf-ish."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    vals = jax.lax.top_k(logits, k)[0]
    kth = vals[..., -1:]
    return jnp.where(logits >= kth, logits,
                     jnp.asarray(_NEG_INF, logits.dtype))


@register("_contrib_sample_top_k", aliases=("sample_top_k",),
          differentiable=False)
def sample_top_k(logits, key, k=0, temperature=1.0):
    """Top-k filtered temperature sampling. ``k`` is static (baked into
    the trace); ``k <= 0`` means no filtering."""
    return sample_temperature.fn(_top_k_filter(logits, int(k)),
                                 _as_key(key), temperature)


@register("_contrib_generation_sample", aliases=("generation_sample",),
          differentiable=False)
def generation_sample(logits, key, temperatures, k=0):
    """The fused serving sampler: per-row temperatures ``(B,)`` over
    ``logits (B, V)`` (0 => greedy for that row), optional static top-k.
    One op so the whole mixed-policy slot batch samples in one program."""
    return sample_top_k.fn(logits, key, k=int(k), temperature=temperatures)


@register("_contrib_kv_cache_update", aliases=("kv_cache_update",),
          differentiable=False)
def kv_cache_update(cache, new, positions):
    """Write ``new (B, n, ...)`` into ``cache (B, S, ...)`` at per-row
    offsets ``positions (B,)`` along axis 1 — a vmapped
    ``dynamic_update_slice``, the per-slot cache append of the decode
    step. Out-of-range positions clamp (lax semantics); callers retire
    slots before they reach ``S``."""
    pos = jnp.asarray(positions, dtype=jnp.int32)

    def _row(c, n, p):
        return jax.lax.dynamic_update_slice_in_dim(c, n, p, axis=0)

    return jax.vmap(_row)(cache, jnp.asarray(new, cache.dtype), pos)


@register("_contrib_arena_slice", aliases=("arena_slice",),
          differentiable=False)
def arena_slice(arena, index, size=1, axis=1):
    """Read ``size`` rows of ``arena`` at offset ``index`` (traced scalar)
    on ``axis``, full extent on every other axis — the inverse of
    :func:`arena_update`, used by the chunked-prefill program to pull one
    slot's K/V rows out of the ``(layers, slots, seq, heads, head_dim)``
    arena and by the prefix cache to extract a reusable slab. ``size`` is
    static; out-of-range indices clamp (lax semantics)."""
    starts = [jnp.asarray(0, jnp.int32)] * arena.ndim
    starts[int(axis)] = jnp.asarray(index, jnp.int32).reshape(())
    sizes = list(arena.shape)
    sizes[int(axis)] = int(size)
    return jax.lax.dynamic_slice(arena, tuple(starts), tuple(sizes))


@register("_contrib_arena_update", aliases=("arena_update",),
          differentiable=False)
def arena_update(arena, block, index, axis=1):
    """Write ``block`` into ``arena`` at offset ``index`` (traced scalar)
    on ``axis``, 0 on every other axis — the prefill's slot write into the
    ``(layers, slots, seq, heads, head_dim)`` K/V arena. ``block`` must
    match ``arena``'s rank (use a size-1 ``axis`` dim for one slot)."""
    starts = [jnp.asarray(0, jnp.int32)] * arena.ndim
    starts[int(axis)] = jnp.asarray(index, jnp.int32).reshape(())
    return jax.lax.dynamic_update_slice(
        arena, jnp.asarray(block, arena.dtype), tuple(starts))
