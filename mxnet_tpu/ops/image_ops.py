"""Image operators.

Role parity: reference ``src/operator/image/image_random.cc`` and
``resize.cc`` / ``crop.cc`` (_image_* registrations behind
mx.nd.image.* / npx.image.*). HWC layout (trailing channel), batched
leading dims allowed — same contract as the reference. Random-augment ops
bind RNG keys at invoke (state_binders) like every stochastic op here.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ._common import _bind_key, _RNG, _dt  # noqa: F401
from .registry import register

_NPX = "_npx__image_"






@register("_image_to_tensor", aliases=(_NPX + "to_tensor", "image_to_tensor"))
def _image_to_tensor(data):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference image_random.cc
    ToTensor); batched NHWC -> NCHW."""
    x = data.astype(jnp.float32) / 255.0
    if data.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize", aliases=(_NPX + "normalize", "image_normalize"))
def _image_normalize(data, mean=0.0, std=1.0):
    """Channel-wise (x - mean) / std on CHW/NCHW tensors (reference
    NormalizeOpForward)."""
    c_axis = 0 if data.ndim == 3 else 1
    shape = [1] * data.ndim
    shape[c_axis] = -1
    mean = jnp.reshape(jnp.atleast_1d(jnp.asarray(mean, data.dtype)), shape) \
        if _np.ndim(mean) or isinstance(mean, (tuple, list)) else mean
    std = jnp.reshape(jnp.atleast_1d(jnp.asarray(std, data.dtype)), shape) \
        if _np.ndim(std) or isinstance(std, (tuple, list)) else std
    return (data - mean) / std


@register("_image_crop", aliases=(_NPX + "crop", "image_crop"))
def _image_crop(data, x=0, y=0, width=1, height=1):
    """Fixed crop of HWC/NHWC images (reference crop.cc)."""
    sl = (slice(int(y), int(y) + int(height)),
          slice(int(x), int(x) + int(width)), slice(None))
    return data[(Ellipsis,) + sl]  # trailing HWC, any number of batch dims


@register("_image_resize", aliases=(_NPX + "resize", "image_resize"))
def _image_resize(data, size=None, keep_ratio=False, interp=1):
    """Bilinear/nearest resize of HWC/NHWC (reference resize.cc)."""
    if isinstance(size, int):
        size = (size, size)
    w, h = int(size[0]), int(size[1])
    method = "nearest" if int(interp) == 0 else "linear"
    if data.ndim == 3:
        out_shape = (h, w, data.shape[2])
    else:
        out_shape = (data.shape[0], h, w, data.shape[3])
    return jax.image.resize(data.astype(jnp.float32), out_shape,
                            method=method).astype(data.dtype)


@register("_image_flip_left_right",
          aliases=(_NPX + "flip_left_right", "image_flip_left_right"))
def _image_flip_left_right(data):
    return jnp.flip(data, axis=-2)


@register("_image_flip_top_bottom",
          aliases=(_NPX + "flip_top_bottom", "image_flip_top_bottom"))
def _image_flip_top_bottom(data):
    return jnp.flip(data, axis=-3)


@register("_image_random_flip_left_right",
          aliases=(_NPX + "random_flip_left_right",),
          differentiable=False, state_binders=_RNG)
def _image_random_flip_left_right(data, key=None):
    flip = jax.random.bernoulli(key)
    return jnp.where(flip, jnp.flip(data, axis=-2), data)


@register("_image_random_flip_top_bottom",
          aliases=(_NPX + "random_flip_top_bottom",),
          differentiable=False, state_binders=_RNG)
def _image_random_flip_top_bottom(data, key=None):
    flip = jax.random.bernoulli(key)
    return jnp.where(flip, jnp.flip(data, axis=-3), data)


def _blend(a, b, w):
    return a * w + b * (1.0 - w)


def _to_gray(x):
    # ITU-R BT.601 luma weights, HWC trailing channel
    wts = jnp.asarray([0.299, 0.587, 0.114], x.dtype)
    gray = jnp.sum(x * wts, axis=-1, keepdims=True)
    return jnp.broadcast_to(gray, x.shape)


@register("_image_random_brightness",
          aliases=(_NPX + "random_brightness",),
          differentiable=False, state_binders=_RNG)
def _image_random_brightness(data, min_factor=0.0, max_factor=1.0, key=None):
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    return data * f


@register("_image_random_contrast",
          aliases=(_NPX + "random_contrast",),
          differentiable=False, state_binders=_RNG)
def _image_random_contrast(data, min_factor=0.0, max_factor=1.0, key=None):
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    x = data.astype(jnp.float32)
    mean = jnp.mean(_to_gray(x)[..., :1])
    return _blend(x, jnp.full_like(x, mean), f).astype(data.dtype)


@register("_image_random_saturation",
          aliases=(_NPX + "random_saturation",),
          differentiable=False, state_binders=_RNG)
def _image_random_saturation(data, min_factor=0.0, max_factor=1.0, key=None):
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    x = data.astype(jnp.float32)
    return _blend(x, _to_gray(x), f).astype(data.dtype)


@register("_image_random_hue", aliases=(_NPX + "random_hue",),
          differentiable=False, state_binders=_RNG)
def _image_random_hue(data, min_factor=0.0, max_factor=1.0, key=None):
    """Hue rotation via the YIQ linear approximation (reference
    RandomHue uses the same linearized transform)."""
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    theta = f * jnp.pi
    x = data.astype(jnp.float32)
    c, s = jnp.cos(theta), jnp.sin(theta)
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], jnp.float32)
    t_rgb = jnp.linalg.inv(t_yiq)
    rot = jnp.asarray([[1.0, 0.0, 0.0],
                       [0.0, c, -s],
                       [0.0, s, c]], jnp.float32)
    m = t_rgb @ rot @ t_yiq
    return jnp.einsum("...c,dc->...d", x, m).astype(data.dtype)


@register("_image_random_lighting", aliases=(_NPX + "random_lighting",),
          differentiable=False, state_binders=_RNG)
def _image_random_lighting(data, alpha_std=0.05, key=None):
    """AlexNet-style PCA lighting noise (reference RandomLighting, fixed
    ImageNet eigen-basis)."""
    eigval = jnp.asarray([55.46, 4.794, 1.148], jnp.float32)
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]], jnp.float32)
    alpha = jax.random.normal(key, (3,)) * alpha_std
    delta = eigvec @ (alpha * eigval)
    return (data.astype(jnp.float32) + delta).astype(data.dtype)


@register("_image_adjust_lighting", aliases=(_NPX + "adjust_lighting",))
def _image_adjust_lighting(data, alpha=None):
    eigval = jnp.asarray([55.46, 4.794, 1.148], jnp.float32)
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]], jnp.float32)
    delta = eigvec @ (jnp.asarray(alpha, jnp.float32) * eigval)
    return (data.astype(jnp.float32) + delta).astype(data.dtype)


@register("_image_random_color_jitter",
          aliases=(_NPX + "random_color_jitter",),
          differentiable=False, state_binders=_RNG)
def _image_random_color_jitter(data, brightness=0.0, contrast=0.0,
                               saturation=0.0, hue=0.0, key=None):
    kb, kc, ks, kh = jax.random.split(key, 4)
    x = data
    if brightness > 0:
        x = _image_random_brightness.fn(x, 1 - brightness, 1 + brightness,
                                        key=kb)
    if contrast > 0:
        x = _image_random_contrast.fn(x, 1 - contrast, 1 + contrast, key=kc)
    if saturation > 0:
        x = _image_random_saturation.fn(x, 1 - saturation, 1 + saturation,
                                        key=ks)
    if hue > 0:
        x = _image_random_hue.fn(x, -hue, hue, key=kh)
    return x
