"""Straight-through estimators, gradient multiplier, scatter scalar ops,
and the DGL graph op registry names.

Role parity:
- reference ``src/operator/contrib/stes_op.cc:34`` (`_contrib_round_ste`,
  `_contrib_sign_ste`): forward = round/sign, backward = identity —
  here ``jax.custom_vjp`` instead of a registered backward op (the tape's
  jax.vjp replay honors custom_vjp automatically);
- reference ``src/operator/contrib/gradient_multiplier_op.cc:73``
  (`_contrib_gradientmultiplier`): identity forward, gradient scaled by
  ``scalar`` on the way back;
- reference ``src/operator/tensor/elemwise_scatter_op.cc:74-121``
  (`_scatter_elemwise_div`, `_scatter_{plus,minus}_scalar`): on sparse
  lhs the op applies only to stored values; dense inputs behave exactly
  like the plain ops (this build's sparse frontend keeps compressed
  payloads at the NDArray layer, so the registry kernels are the dense
  path — `ndarray/sparse.py` routes stored-value arithmetic);
- reference ``src/operator/contrib/dgl_graph.cc`` / ``contrib/nnz.cc``:
  the DGL sampler/adjacency/edge_id family — host-side CSR kernels in the
  reference too (CPU FComputeEx), registered here as host ops delegating
  to ``mxnet_tpu.contrib.graph``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["round_ste", "sign_ste", "gradientmultiplier"]


@jax.custom_vjp
def _round_ste(x):
    return jnp.round(x)


_round_ste.defvjp(lambda x: (jnp.round(x), None),
                  lambda _, g: (g,))


@jax.custom_vjp
def _sign_ste(x):
    return jnp.sign(x)


_sign_ste.defvjp(lambda x: (jnp.sign(x), None),
                 lambda _, g: (g,))


@register("_contrib_round_ste", aliases=("round_ste",))
def round_ste(data):
    """round() forward, identity gradient (stes_op.cc:34)."""
    return _round_ste(data)


@register("_contrib_sign_ste", aliases=("sign_ste",))
def sign_ste(data):
    """sign() forward, identity gradient (stes_op.cc)."""
    return _sign_ste(data)


@jax.custom_vjp
def _gradmul(x, lam):
    return x


_gradmul.defvjp(lambda x, lam: (x, lam),
                lambda lam, g: (g * lam, jnp.zeros_like(lam)))


@register("_contrib_gradientmultiplier",
          aliases=("gradientmultiplier",))
def gradientmultiplier(data, scalar=1.0):
    """Identity forward; backward multiplies the incoming gradient by
    ``scalar`` (gradient_multiplier_op.cc:73 — the GRL building block)."""
    return _gradmul(data, jnp.asarray(scalar, data.dtype))


@register("_scatter_plus_scalar")
def scatter_plus_scalar(data, scalar=0.0):
    """data + scalar applied to stored values only for sparse inputs;
    identical to _plus_scalar on dense (elemwise_scatter_op.cc:100)."""
    return data + scalar


@register("_scatter_minus_scalar")
def scatter_minus_scalar(data, scalar=0.0):
    """data - scalar on stored values (elemwise_scatter_op.cc:121)."""
    return data - scalar


@register("_scatter_elemwise_div")
def scatter_elemwise_div(lhs, rhs):
    """lhs / rhs with output storage following lhs
    (elemwise_scatter_op.cc:74)."""
    return lhs / rhs


def _graph(name):
    def call(*args, **kwargs):
        from ..contrib import graph as g
        return getattr(g, name)(*args, **kwargs)
    call.__name__ = name
    call.__doc__ = "host op -> mxnet_tpu.contrib.graph.%s" % name
    return call


for _ref_name, _fn_name in [
        ("_contrib_edge_id", "edge_id"),
        ("_contrib_getnnz", "getnnz"),
        ("_contrib_dgl_adjacency", "dgl_adjacency"),
        ("_contrib_dgl_subgraph", "dgl_subgraph"),
        ("_contrib_dgl_csr_neighbor_uniform_sample",
         "dgl_csr_neighbor_uniform_sample"),
        ("_contrib_dgl_csr_neighbor_non_uniform_sample",
         "dgl_csr_neighbor_non_uniform_sample"),
        ("_contrib_dgl_graph_compact", "dgl_graph_compact")]:
    register(_ref_name, host_op=True, differentiable=False)(_graph(_fn_name))

