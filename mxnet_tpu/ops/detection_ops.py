"""Detection / contrib vision operators.

Role parity: reference ``src/operator/contrib/`` detection family —
`bounding_box.cc` (_contrib_box_nms :38, _contrib_box_iou :120,
_contrib_bipartite_matching :161, _contrib_box_encode :208,
_contrib_box_decode :230), `multibox_prior.cc:103`,
`roi_align.cc`, `bilinear_resize.cc`, `adaptive_avg_pooling.cc`,
`boolean_mask.cc`, `allclose_op.cc`, `all_finite.cc`, `erfinv-inl.h`.

TPU-native design: every kernel is static-shape XLA — NMS is a
fixed-trip-count `lax.fori_loop` over a precomputed IoU matrix (suppressed
rows become -1, no dynamic compaction), bipartite matching greedily
consumes an (N, M) score matrix the same way, ROIAlign is vectorized
bilinear gather, adaptive pooling uses integral images. `boolean_mask` is
the one inherently-dynamic op: eager-only, with a clear error under
tracing (the reference's dynamic-shape ops have the same caveat on
accelerators).
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_MIN = -3.4e38


def _to_corner(b, fmt):
    if fmt == "corner":
        return b
    # center (x, y, w, h) -> corner
    x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _from_corner(b, fmt):
    if fmt == "corner":
        return b
    x1, y1, x2, y2 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                     axis=-1)


def _iou_corner(a, b):
    """a (..., N, 4), b (..., M, 4) corner boxes -> (..., N, M) IoU."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner"):
    """reference `bounding_box.cc:120` — pairwise IoU."""
    return _iou_corner(_to_corner(lhs, format), _to_corner(rhs, format))


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """reference `bounding_box.cc:38` — greedy per-batch NMS. Entries are
    sorted by score descending; suppressed/invalid entries become -1.
    Static-shape: output has the input's (..., N, K) shape."""
    orig_shape = data.shape
    k = orig_shape[-1]
    n = orig_shape[-2]
    flat = data.reshape((-1, n, k))

    def one(batch):
        scores = batch[:, score_index]
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid = valid & (batch[:, id_index] != background_id)
        order = jnp.argsort(jnp.where(valid, -scores, jnp.inf))
        sorted_b = batch[order]
        sorted_valid = valid[order]
        if topk > 0:
            sorted_valid = sorted_valid & (jnp.arange(n) < topk)
        boxes = _to_corner(sorted_b[:, coord_start:coord_start + 4],
                           in_format)
        iou = _iou_corner(boxes, boxes)
        same_class = (jnp.ones((n, n), bool) if (force_suppress or
                                                 id_index < 0)
                      else (sorted_b[:, id_index][:, None] ==
                            sorted_b[:, id_index][None, :]))
        suppress_mat = (iou > overlap_thresh) & same_class

        def body(i, keep):
            # i suppresses later j when i itself is kept
            row = suppress_mat[i] & (jnp.arange(n) > i) & keep[i]
            return keep & ~row
        keep = lax.fori_loop(0, n, body, sorted_valid)
        out_b = sorted_b
        if out_format != in_format:
            coords = _from_corner(boxes, out_format)
            out_b = out_b.at[:, coord_start:coord_start + 4].set(coords)
        return jnp.where(keep[:, None], out_b,
                         jnp.full_like(out_b, -1.0))

    out = jax.vmap(one)(flat)
    return out.reshape(orig_shape)


@register("_contrib_bipartite_matching", aliases=("bipartite_matching",),
          n_out=2)
def bipartite_matching(data, is_ascend=False, threshold=0.5, topk=-1):
    """reference `bounding_box.cc:161` — greedy bipartite matching on a
    (..., N, M) score matrix. Returns (row->col matches (..., N), col->row
    matches (..., M)); unmatched = -1."""
    orig = data.shape
    n, m = orig[-2], orig[-1]
    flat = data.reshape((-1, n, m))
    steps = n if topk <= 0 else min(topk, n)

    def one(mat):
        work = mat if not is_ascend else -mat
        thr = threshold if not is_ascend else -threshold

        def body(_, state):
            work, row_match, col_match = state
            idx = jnp.argmax(work)
            i, j = idx // m, idx % m
            ok = work[i, j] >= thr
            row_match = jnp.where(ok, row_match.at[i].set(j), row_match)
            col_match = jnp.where(ok, col_match.at[j].set(i), col_match)
            work = jnp.where(ok, work.at[i, :].set(_MIN), work)
            work = jnp.where(ok, work.at[:, j].set(_MIN), work)
            return work, row_match, col_match

        _, row_match, col_match = lax.fori_loop(
            0, steps, body,
            (work, jnp.full((n,), -1.0, mat.dtype),
             jnp.full((m,), -1.0, mat.dtype)))
        return row_match, col_match

    rows, cols = jax.vmap(one)(flat)
    return (rows.reshape(orig[:-1]), cols.reshape(orig[:-2] + (m,)))


@register("_contrib_box_encode", aliases=("box_encode",))
def box_encode(samples, matches, anchors, refs,
               means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2)):
    """reference `bounding_box.cc:208` — SSD-style target encoding.
    samples (B, N) in {-1, 0, 1}, matches (B, N) ref indices, anchors
    (B, N, 4) corner, refs (B, M, 4) corner. Returns (targets, masks)."""
    matched = jnp.take_along_axis(
        refs, jnp.maximum(matches, 0).astype(jnp.int32)[..., None]
        .repeat(4, axis=-1), axis=1)
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = (anchors[..., 0] + anchors[..., 2]) / 2
    ay = (anchors[..., 1] + anchors[..., 3]) / 2
    gw = matched[..., 2] - matched[..., 0]
    gh = matched[..., 3] - matched[..., 1]
    gx = (matched[..., 0] + matched[..., 2]) / 2
    gy = (matched[..., 1] + matched[..., 3]) / 2
    means = jnp.asarray(means, anchors.dtype)
    stds = jnp.asarray(stds, anchors.dtype)
    t = jnp.stack([(gx - ax) / jnp.maximum(aw, 1e-12),
                   (gy - ay) / jnp.maximum(ah, 1e-12),
                   jnp.log(jnp.maximum(gw, 1e-12) /
                           jnp.maximum(aw, 1e-12)),
                   jnp.log(jnp.maximum(gh, 1e-12) /
                           jnp.maximum(ah, 1e-12))], axis=-1)
    t = (t - means) / stds
    mask = (samples > 0.5).astype(anchors.dtype)[..., None]
    return t * mask, jnp.broadcast_to(mask, t.shape).astype(anchors.dtype)


@register("_contrib_box_decode", aliases=("box_decode",))
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """reference `bounding_box.cc:230` — invert box_encode."""
    a = _to_corner(anchors, format)
    aw = a[..., 2] - a[..., 0]
    ah = a[..., 3] - a[..., 1]
    ax = (a[..., 0] + a[..., 2]) / 2
    ay = (a[..., 1] + a[..., 3]) / 2
    dx = data[..., 0] * std0 * aw + ax
    dy = data[..., 1] * std1 * ah + ay
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    dw = jnp.exp(dw) * aw / 2
    dh = jnp.exp(dh) * ah / 2
    return jnp.stack([dx - dw, dy - dh, dx + dw, dy + dh], axis=-1)


@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",
                                             "multibox_prior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), steps=(-1.0, -1.0),
                   offsets=(0.5, 0.5), clip=False):
    """reference `multibox_prior.cc:103` — anchor box generation over the
    feature map grid of ``data`` (N, C, H, W) -> (1, H*W*A, 4) with
    A = len(sizes) + len(ratios) - 1 (reference convention)."""
    h, w = data.shape[-2], data.shape[-1]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # H,W,2
    sizes = list(sizes)
    ratios = list(ratios)
    whs = []
    for s in sizes:
        r = ratios[0]
        whs.append((s * _np.sqrt(r), s / _np.sqrt(r)))
    for r in ratios[1:]:
        s = sizes[0]
        whs.append((s * _np.sqrt(r), s / _np.sqrt(r)))
    whs = jnp.asarray(whs, jnp.float32)  # (A, 2) = (w, h)
    a = whs.shape[0]
    cxg = jnp.broadcast_to(cyx[..., 1][..., None], (h, w, a))
    cyg = jnp.broadcast_to(cyx[..., 0][..., None], (h, w, a))
    wg = jnp.broadcast_to(whs[:, 0], (h, w, a))
    hg = jnp.broadcast_to(whs[:, 1], (h, w, a))
    boxes = jnp.stack([cxg - wg / 2, cyg - hg / 2,
                       cxg + wg / 2, cyg + hg / 2], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.reshape((1, h * w * a, 4))


@register("_contrib_ROIAlign", aliases=("ROIAlign", "roi_align"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False):
    """reference `roi_align.cc` (contrib ROIAlign) — bilinear-sampled ROI
    pooling. data (N, C, H, W); rois (R, 5) = [batch_idx, x1, y1, x2, y2]
    in image coords; output (R, C, PH, PW), or (R, C/(PH*PW), PH, PW) when
    ``position_sensitive`` (PSROIAlign channel-per-bin selection).

    Deviation from the reference: sample_ratio<=0 ("adaptive" = per-ROI
    ceil(roi_size/pooled_size) samples) is data-dependent and cannot be a
    static XLA shape — it falls back to a fixed 2x2 sample grid per bin.
    """
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    n, c, hh, ww = data.shape
    sr = int(sample_ratio) if int(sample_ratio) > 0 else 2
    offset = 0.5 if aligned else 0.0
    if position_sensitive and c % (ph * pw) != 0:
        raise ValueError(
            "position_sensitive ROIAlign needs channels %% (ph*pw) == 0, "
            "got C=%d for pooled %dx%d" % (c, ph, pw))

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bw = rw / pw
        bh = rh / ph
        # sample grid: (ph, pw, sr, sr)
        iy = jnp.arange(ph, dtype=data.dtype)
        ix = jnp.arange(pw, dtype=data.dtype)
        sy = (jnp.arange(sr, dtype=data.dtype) + 0.5) / sr
        sx = (jnp.arange(sr, dtype=data.dtype) + 0.5) / sr
        ys = y1 + (iy[:, None] + sy[None, :]) * bh  # (ph, sr)
        xs = x1 + (ix[:, None] + sx[None, :]) * bw  # (pw, sr)
        ys = jnp.clip(ys, 0.0, hh - 1.0)
        xs = jnp.clip(xs, 0.0, ww - 1.0)
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy1 = ys - y0
        wx1 = xs - x0
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        y1i = jnp.minimum(y0i + 1, hh - 1)
        x1i = jnp.minimum(x0i + 1, ww - 1)
        img = data[bidx]  # (C, H, W)

        def gather(yi, xi):
            # yi (ph, sr), xi (pw, sr) -> (C, ph, sr, pw, sr)
            return img[:, yi[:, :, None, None], xi[None, None, :, :]]

        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x1i)
        v10 = gather(y1i, x0i)
        v11 = gather(y1i, x1i)
        wy1b = wy1[None, :, :, None, None]
        wx1b = wx1[None, None, None, :, :]
        val = (v00 * (1 - wy1b) * (1 - wx1b) + v01 * (1 - wy1b) * wx1b +
               v10 * wy1b * (1 - wx1b) + v11 * wy1b * wx1b)
        pooled = val.mean(axis=(2, 4))  # (C, ph, pw)
        if position_sensitive:
            # channel co*ph*pw + iy*pw + ix feeds output bin (co, iy, ix)
            c_out = c // (ph * pw)
            grp = pooled.reshape((c_out, ph * pw, ph, pw))
            bin_idx = (jnp.arange(ph)[:, None] * pw +
                       jnp.arange(pw)[None, :])           # (ph, pw)
            pooled = jnp.take_along_axis(
                grp, bin_idx[None, None, :, :].repeat(c_out, 0),
                axis=1)[:, 0]
        return pooled

    return jax.vmap(one)(rois)


@register("_contrib_BilinearResize2D", aliases=("BilinearResize2D",
                                                "bilinear_resize_2d"))
def bilinear_resize_2d(data, height=None, width=None, scale_height=None,
                       scale_width=None, like=None, mode="size"):
    """reference `bilinear_resize.cc` — NCHW bilinear resize via
    jax.image.resize. Modes: explicit height/width, scale_height/_width
    ("odd_scale"-style), or mode="like" with a reference tensor."""
    n, c, h, w = data.shape
    if like is not None or mode == "like":
        if like is None:
            raise ValueError("mode='like' requires the `like` tensor")
        height, width = like.shape[-2], like.shape[-1]
    elif height is None:
        if scale_height is None:
            raise ValueError("BilinearResize2D needs height/width, "
                             "scale_height/scale_width, or like=")
        height = int(round(h * scale_height))
        width = int(round(w * (scale_width if scale_width is not None
                               else scale_height)))
    elif width is None:
        raise ValueError("BilinearResize2D: height given without width")
    out_shape = (n, c, int(height), int(width))
    return jax.image.resize(data, out_shape, method="linear")


@register("_contrib_AdaptiveAvgPooling2D",
          aliases=("AdaptiveAvgPooling2D", "adaptive_avg_pool2d"))
def adaptive_avg_pooling_2d(data, output_size=(1, 1)):
    """reference `adaptive_avg_pooling.cc` — exact variable-window average
    pooling via integral images (cumsum), torch-compatible windows."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = data.shape
    if (oh, ow) == (1, 1):  # global pooling: one reduction, no cumsums
        return data.mean(axis=(2, 3), keepdims=True)
    # integral image with leading zero row/col
    ii = jnp.pad(jnp.cumsum(jnp.cumsum(data.astype(jnp.float32), axis=2),
                            axis=3), ((0, 0), (0, 0), (1, 0), (1, 0)))
    ys = (_np.arange(oh) * h) // oh
    ye = -(-(_np.arange(1, oh + 1) * h) // oh)
    xs = (_np.arange(ow) * w) // ow
    xe = -(-(_np.arange(1, ow + 1) * w) // ow)
    out = (ii[:, :, ye[:, None], xe[None, :]]
           - ii[:, :, ys[:, None], xe[None, :]]
           - ii[:, :, ye[:, None], xs[None, :]]
           + ii[:, :, ys[:, None], xs[None, :]])
    areas = ((ye - ys)[:, None] * (xe - xs)[None, :]).astype(_np.float32)
    return (out / areas).astype(data.dtype)


@register("_contrib_boolean_mask", aliases=("boolean_mask",),
          differentiable=False)
def boolean_mask(data, index, axis=0):
    """reference `boolean_mask.cc` — dynamic-shape row filter. Eager-only
    on TPU (XLA requires static shapes); under tracing raises with
    guidance to use `where`/`sparse_retain`-style masking instead."""
    if isinstance(data, jax.core.Tracer) or isinstance(index,
                                                       jax.core.Tracer):
        raise TypeError(
            "boolean_mask produces a data-dependent shape and cannot run "
            "inside jit/hybridize on TPU; use elementwise masking "
            "(where/sparse_retain) or run it eagerly")
    keep = _np.asarray(index).astype(bool)
    return jnp.compress(keep, data, axis=axis)


@register("_contrib_allclose", aliases=("allclose",))
def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    """reference `allclose_op.cc` — scalar 0/1 tensor."""
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype(jnp.float32)


# NB: all_finite / multi_all_finite (reference all_finite.cc) keep their
# tensor_extra.py registrations with the reference's (1,) output shape,
# and erfinv (reference erfinv-inl.h) its core.py registration.
