"""Shared helpers for op modules: RNG-key state binder and dtype default.

One definition so every stochastic op family binds keys the same way
(deterministic tape replay — see registry.Op.state_binders docstring).
"""
from __future__ import annotations

import numpy as _np

from ..base import dtype_np


def _bind_key():
    from .. import random as _rnd
    return _rnd.next_key()


def _bind_train():
    from .. import _tape
    return _tape.is_training()


_RNG = {"key": _bind_key}


def _dt(dtype, default=_np.float32):
    return default if dtype is None else dtype_np(dtype)
