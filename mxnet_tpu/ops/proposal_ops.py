"""Region-proposal / SSD-training / deformable op family.

Role parity: reference ``src/operator/contrib/multibox_target``/
``multibox_detection`` (SSD anchor matching + decoding, -inl.h kernels),
``contrib/proposal``/``multi_proposal`` (Faster-RCNN RPN proposal
generation), ``contrib/psroi_pooling``, ``contrib/deformable_convolution``
(+ ``nn/deformable_im2col``), ``contrib/deformable_psroi_pooling``, and
``contrib/rroi_align``.

TPU-first notes: everything is static-shape — proposal top-k counts are
compile-time constants, suppressed entries are masked (-1 / zero rows)
rather than compacted, and the greedy NMS is the fori_loop kernel shared
with ``box_nms``. Deformable sampling is expressed as K*K bilinear gathers
+ 1x1 matmuls so the FLOPs still land on the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .detection_ops import _iou_corner, box_nms, roi_align
from .spatial_ops import _sample_one
from .registry import register

__all__ = ["MultiBoxTarget", "MultiBoxDetection", "Proposal",
           "MultiProposal", "PSROIPooling", "DeformableConvolution",
           "DeformablePSROIPooling", "RROIAlign"]


def _corners_to_center(b):
    w = b[..., 2] - b[..., 0]
    h = b[..., 3] - b[..., 1]
    return (b[..., 0] + w / 2, b[..., 1] + h / 2, w, h)


# ------------------------------------------------------------- SSD training

@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",), n_out=3)
def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD target assignment (reference contrib/multibox_target-inl.h).

    anchor (1, N, 4 corner), label (B, M, 5) rows [cls, x1, y1, x2, y2]
    (padded rows cls = -1), cls_pred (B, num_cls+1, N). Returns
    (box_target (B, N*4), box_mask (B, N*4), cls_target (B, N)).
    Matching = per-gt best anchor (bipartite stage) union anchors whose best
    IoU clears ``overlap_threshold``; optional hard-negative mining keeps
    ``negative_mining_ratio`` negatives per positive ranked by max
    non-background confidence.
    """
    A = anchor.reshape(-1, 4)
    N = A.shape[0]
    acx, acy, aw, ah = _corners_to_center(A)
    v0, v1, v2, v3 = (float(v) for v in variances)

    def one(lab, pred):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _iou_corner(A, gt_boxes)                     # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)                  # per anchor
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou > overlap_threshold
        # bipartite stage: each valid gt claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)              # (M,)
        forced = jnp.zeros((N,), bool)
        forced_gt = best_gt
        M = lab.shape[0]
        for m in range(M):  # static, M is the (small) label pad length
            a_m = best_anchor[m]
            take = gt_valid[m]
            forced = forced.at[a_m].set(forced[a_m] | take)
            forced_gt = forced_gt.at[a_m].set(
                jnp.where(take, m, forced_gt[a_m]))
        matched = matched | forced
        match_id = jnp.where(forced, forced_gt, best_gt)

        g = gt_boxes[match_id]
        gcx, gcy, gw, gh = _corners_to_center(g)
        eps = 1e-8
        t = jnp.stack([(gcx - acx) / (aw + eps) / v0,
                       (gcy - acy) / (ah + eps) / v1,
                       jnp.log(jnp.maximum(gw / (aw + eps), eps)) / v2,
                       jnp.log(jnp.maximum(gh / (ah + eps), eps)) / v3],
                      axis=-1)
        box_target = jnp.where(matched[:, None], t, 0.0).reshape(-1)
        box_mask = jnp.where(matched[:, None],
                             jnp.ones_like(t), 0.0).reshape(-1)
        cls = jnp.where(matched, lab[match_id, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            neg_conf = jnp.max(pred[1:], axis=0)           # (N,)
            neg_score = jnp.where(matched, -jnp.inf,
                                  jnp.where(neg_conf > negative_mining_thresh,
                                            neg_conf, -jnp.inf))
            order = jnp.argsort(-neg_score)
            rank = jnp.zeros((N,), jnp.int32).at[order].set(
                jnp.arange(N, dtype=jnp.int32))
            num_pos = jnp.sum(matched)
            num_neg = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                minimum_negative_samples)
            keep_neg = (rank < num_neg) & jnp.isfinite(neg_score)
            cls = jnp.where(matched, cls,
                            jnp.where(keep_neg, 0.0, float(ignore_label)))
        return box_target, box_mask, cls

    bt, bm, ct = jax.vmap(one)(label, cls_pred)
    return bt, bm, ct


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",))
def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5,
                      force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD decode + per-class NMS (reference contrib/multibox_detection).

    cls_prob (B, num_cls+1, N), loc_pred (B, N*4), anchor (1, N, 4).
    Returns (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], -1 = suppressed.
    """
    B, _, N = cls_prob.shape
    A = anchor.reshape(-1, 4)
    acx, acy, aw, ah = _corners_to_center(A)
    v0, v1, v2, v3 = (float(v) for v in variances)
    winner = jnp.argmax(cls_prob, axis=1)                    # (B, N)
    score = jnp.max(cls_prob, axis=1)
    # output ids are foreground-indexed: background wins -> invalid row
    cls_id = (winner - (winner > background_id)).astype(cls_prob.dtype)
    score = jnp.where(winner == background_id, -1.0, score)
    p = loc_pred.reshape(B, N, 4)
    cx = p[..., 0] * v0 * aw + acx
    cy = p[..., 1] * v1 * ah + acy
    w = jnp.exp(p[..., 2] * v2) * aw
    h = jnp.exp(p[..., 3] * v3) * ah
    boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    det = jnp.concatenate([cls_id[..., None], score[..., None], boxes], -1)
    det = jnp.where(score[..., None] > threshold, det, -1.0)
    return box_nms.fn(det, overlap_thresh=nms_threshold,
                      valid_thresh=threshold, topk=nms_topk, coord_start=2,
                      score_index=1, id_index=0, background_id=-1,
                      force_suppress=force_suppress)


# ----------------------------------------------------------------- RPN ops

def _gen_base_anchors(base_size, ratios, scales, dtype):
    """Faster-RCNN anchor enumeration, ratio-major then scale (reference
    contrib/proposal-inl.h GenerateAnchors)."""
    out = []
    cx = cy = (base_size - 1) / 2.0
    area = float(base_size * base_size)
    for r in ratios:
        ws = round((area / r) ** 0.5)
        hs = round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            out.append([cx - (w - 1) / 2, cy - (h - 1) / 2,
                        cx + (w - 1) / 2, cy + (h - 1) / 2])
    return jnp.asarray(out, dtype)


def _proposal_one(score, deltas, im_info, base, feature_stride,
                  pre_nms, post_nms, nms_thresh, min_size):
    """score (A, H, W) foreground probs; deltas (A*4, H, W); returns
    (post_nms, 4) boxes + (post_nms,) scores (zero rows when suppressed)."""
    An, H, W = score.shape
    dt = score.dtype
    sy = jnp.arange(H, dtype=dt) * feature_stride
    sx = jnp.arange(W, dtype=dt) * feature_stride
    shift = jnp.stack(jnp.broadcast_arrays(
        sx[None, :], sy[:, None], sx[None, :], sy[:, None]), -1)  # (H, W, 4)
    anchors = base[:, None, None, :] + shift[None]               # (A, H, W, 4)
    acx, acy, aw, ah = _corners_to_center(anchors)
    d = deltas.reshape(An, 4, H, W)
    cx = d[:, 0] * aw + acx
    cy = d[:, 1] * ah + acy
    w = jnp.exp(d[:, 2]) * aw
    h = jnp.exp(d[:, 3]) * ah
    x1 = jnp.clip(cx - (w - 1) / 2, 0, im_info[1] - 1)
    y1 = jnp.clip(cy - (h - 1) / 2, 0, im_info[0] - 1)
    x2 = jnp.clip(cx + (w - 1) / 2, 0, im_info[1] - 1)
    y2 = jnp.clip(cy + (h - 1) / 2, 0, im_info[0] - 1)
    ms = min_size * im_info[2]
    ok = ((x2 - x1 + 1) >= ms) & ((y2 - y1 + 1) >= ms)
    flat_s = jnp.where(ok, score, -jnp.inf).reshape(-1)
    flat_b = jnp.stack([x1, y1, x2, y2], -1).reshape(-1, 4)
    k1 = min(pre_nms, flat_s.shape[0])
    top_s, idx = lax.top_k(flat_s, k1)
    top_b = flat_b[idx]
    det = jnp.concatenate([jnp.zeros((k1, 1), dt), top_s[:, None], top_b],
                          -1)
    det = jnp.where(jnp.isfinite(top_s)[:, None], det, -1.0)
    kept = box_nms.fn(det[None], overlap_thresh=nms_thresh,
                      valid_thresh=-1e30, topk=-1, coord_start=2,
                      score_index=1, id_index=-1)[0]
    ks = jnp.where(kept[:, 1] > -1, kept[:, 1], -jnp.inf)
    k2 = min(post_nms, k1)
    fin_s, fidx = lax.top_k(ks, k2)
    fin_b = kept[fidx, 2:6]
    good = jnp.isfinite(fin_s)
    return (jnp.where(good[:, None], fin_b, 0.0),
            jnp.where(good, fin_s, 0.0))


@register("_contrib_Proposal", aliases=("Proposal",), n_out=0)
def Proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """RPN proposal generation, batch 1 (reference contrib/proposal.cc).
    Returns rois (post_nms, 5) [0, x1, y1, x2, y2] (+ scores (post_nms, 1))."""
    if iou_loss:
        raise NotImplementedError("iou_loss decoding is not supported")
    Anum = len(scales) * len(ratios)
    base = _gen_base_anchors(feature_stride, ratios, scales, cls_prob.dtype)
    boxes, scores = _proposal_one(
        cls_prob[0, Anum:], bbox_pred[0], im_info[0], base,
        float(feature_stride), int(rpn_pre_nms_top_n),
        int(rpn_post_nms_top_n), float(threshold), float(rpn_min_size))
    rois = jnp.concatenate([jnp.zeros((boxes.shape[0], 1), boxes.dtype),
                            boxes], -1)
    if output_score:
        return rois, scores[:, None]
    return (rois,)


@register("_contrib_MultiProposal", aliases=("MultiProposal",), n_out=0)
def MultiProposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                  rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                  scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                  feature_stride=16, output_score=False, iou_loss=False):
    """Batched Proposal (reference contrib/multi_proposal.cc): rois
    (B*post_nms, 5) with the batch index in column 0."""
    if iou_loss:
        raise NotImplementedError("iou_loss decoding is not supported")
    B = cls_prob.shape[0]
    Anum = len(scales) * len(ratios)
    base = _gen_base_anchors(feature_stride, ratios, scales, cls_prob.dtype)

    def one(score, deltas, info):
        return _proposal_one(score, deltas, info, base,
                             float(feature_stride), int(rpn_pre_nms_top_n),
                             int(rpn_post_nms_top_n), float(threshold),
                             float(rpn_min_size))

    boxes, scores = jax.vmap(one)(cls_prob[:, Anum:], bbox_pred, im_info)
    n = boxes.shape[1]
    bidx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype), n)[:, None]
    rois = jnp.concatenate([bidx, boxes.reshape(B * n, 4)], -1)
    if output_score:
        return rois, scores.reshape(B * n, 1)
    return (rois,)


# --------------------------------------------------- PS / deformable pooling

@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def PSROIPooling(data, rois, spatial_scale=1.0, output_dim=0, pooled_size=7,
                 group_size=0):
    """Position-sensitive ROI pooling (reference contrib/psroi_pooling):
    output bin (i, j) of a pooled p x p grid reads channel group
    (floor(i*g/p), floor(j*g/p)) of the g x g score maps.
    Deviation: bins are sampled with the ROIAlign bilinear 2x2 grid instead
    of integer-bin averaging — static shapes, and strictly more accurate."""
    p = int(pooled_size)
    g = int(group_size) or p
    C = data.shape[1]
    cdim = C // (g * g)
    pooled = roi_align.fn(data, rois, pooled_size=(p, p),
                          spatial_scale=float(spatial_scale),
                          sample_ratio=2, position_sensitive=False)
    grp = pooled.reshape(pooled.shape[0], cdim, g * g, p, p)
    gi = (jnp.arange(p) * g) // p                       # (p,)
    bin_idx = gi[:, None] * g + gi[None, :]             # (p, p)
    sel = jnp.take_along_axis(
        grp, jnp.broadcast_to(bin_idx[None, None, None],
                              (pooled.shape[0], cdim, 1, p, p)), axis=2)
    return sel[:, :, 0]


@register("_contrib_RROIAlign", aliases=("RROIAlign",))
def RROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sampling_ratio=2):
    """Rotated ROIAlign (reference contrib/rroi_align.cc): rois (R, 6) =
    [batch_idx, cx, cy, w, h, theta_degrees]; the bin grid is rotated by
    theta about the ROI center before bilinear sampling."""
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    sr = max(int(sampling_ratio), 1)

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        w = jnp.maximum(roi[3] * spatial_scale, 1e-3)
        h = jnp.maximum(roi[4] * spatial_scale, 1e-3)
        th = roi[5] * (jnp.pi / 180.0)
        yy = (jnp.arange(ph * sr, dtype=data.dtype) + 0.5) / (ph * sr) - 0.5
        xx = (jnp.arange(pw * sr, dtype=data.dtype) + 0.5) / (pw * sr) - 0.5
        gy, gx = jnp.meshgrid(yy * h, xx * w, indexing="ij")
        ct, st = jnp.cos(th), jnp.sin(th)
        sx = cx + gx * ct - gy * st
        sy = cy + gx * st + gy * ct
        val = _sample_one(data[bidx], sx, sy)       # (C, ph*sr, pw*sr)
        C = val.shape[0]
        return val.reshape(C, ph, sr, pw, sr).mean(axis=(2, 4))

    return jax.vmap(one)(rois)


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",), n_out=0)
def DeformablePSROIPooling(data, rois, trans=None, spatial_scale=1.0,
                           output_dim=0, group_size=1, pooled_size=7,
                           part_size=0, sample_per_part=2, trans_std=0.1,
                           no_trans=False):
    """Deformable position-sensitive ROI pooling (reference
    contrib/deformable_psroi_pooling.cc): each output bin's sampling window
    is shifted by a learned normalized offset ``trans`` (R, 2*cls, p, p)
    scaled by ``trans_std`` and the ROI extent."""
    p = int(pooled_size)
    g = int(group_size) or p
    sr = max(int(sample_per_part), 1)
    C = data.shape[1]
    cdim = C // (g * g)

    def one(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        # reference geometry (deformable_psroi_pooling.cc:85-88): integer-
        # rounded ROI corners, half-pixel shift, end corner inclusive
        # (round-2 advisor finding: the unrounded variant deviated).
        # floor(x + 0.5), not jnp.round: C round() is half-away-from-zero
        # while jnp.round is half-to-even, and ROI coords are >= 0
        x1 = jnp.floor(roi[1] + 0.5) * spatial_scale - 0.5
        y1 = jnp.floor(roi[2] + 0.5) * spatial_scale - 0.5
        w = jnp.maximum(
            (jnp.floor(roi[3] + 0.5) + 1.0) * spatial_scale - 0.5 - x1, 0.1)
        h = jnp.maximum(
            (jnp.floor(roi[4] + 0.5) + 1.0) * spatial_scale - 0.5 - y1, 0.1)
        bw, bh = w / p, h / p
        iy = jnp.arange(p, dtype=data.dtype)
        ix = jnp.arange(p, dtype=data.dtype)
        if tr is None:
            offy = jnp.zeros((p, p), data.dtype)
            offx = jnp.zeros((p, p), data.dtype)
        else:
            # class-agnostic offsets (cls dim 0), resized p <= part_size
            pt = tr.shape[-1]
            yi = jnp.clip((iy * pt / p).astype(jnp.int32), 0, pt - 1)
            xi = jnp.clip((ix * pt / p).astype(jnp.int32), 0, pt - 1)
            offx = tr[0][yi[:, None], xi[None, :]] * trans_std * w
            offy = tr[1][yi[:, None], xi[None, :]] * trans_std * h
        sy = (jnp.arange(sr, dtype=data.dtype) + 0.5) / sr
        sx = (jnp.arange(sr, dtype=data.dtype) + 0.5) / sr
        ys = y1 + (iy[:, None, None, None] + sy[None, None, :, None]) * bh \
            + offy[:, :, None, None]                      # (p, p, sr, 1)
        xs = x1 + (ix[None, :, None, None] + sx[None, None, None, :]) * bw \
            + offx[:, :, None, None]                      # (p, p, 1, sr)
        ys = jnp.broadcast_to(ys, (p, p, sr, sr)).reshape(p, p * sr * sr)
        xs = jnp.broadcast_to(xs, (p, p, sr, sr)).reshape(p, p * sr * sr)
        # ROIAlign convention: clamp sample coords into the feature map
        # instead of zero-padding at the border
        ys = jnp.clip(ys, 0.0, data.shape[2] - 1.0)
        xs = jnp.clip(xs, 0.0, data.shape[3] - 1.0)
        val = _sample_one(data[bidx], xs, ys)             # (C, p, p*sr*sr)
        val = val.reshape(C, p, p, sr * sr).mean(-1)      # (C, p, p)
        grp = val.reshape(cdim, g * g, p, p)
        # bin (i, j) reads score-map group (i*g//p, j*g//p) — reference
        # deformable_psroi_pooling-inl.h gh/gw floor mapping
        gi = (jnp.arange(p) * g) // p
        bin_idx = gi[:, None] * g + gi[None, :]
        sel = jnp.take_along_axis(
            grp, bin_idx[None, None].repeat(cdim, 0), axis=1)[:, 0]
        return sel

    if no_trans or trans is None:
        out = jax.vmap(lambda r: one(r, None))(rois)
    else:
        out = jax.vmap(one)(rois, trans)
    return (out,)


# ------------------------------------------------- deformable convolution

def _deform_conv(data, offset, mask, weight, bias, kernel, stride, pad,
                 dilate, num_group, num_deformable_group, no_bias):
    """Shared v1/v2 deformable conv body: K*K bilinear gathers
    (x modulation mask for v2) followed by one (C*K*K) x O matmul."""
    B, C, H, W = data.shape
    O = weight.shape[0]
    KH, KW = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    ph_, pw_ = int(pad[0]), int(pad[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    dg = int(num_deformable_group)
    if num_group != 1:
        raise NotImplementedError("num_group > 1 not supported")
    Ho = (H + 2 * ph_ - (dh * (KH - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw_ - (dw * (KW - 1) + 1)) // sw + 1
    off = offset.reshape(B, dg, KH * KW, 2, Ho, Wo)
    if mask is not None:
        mask = mask.reshape(B, dg, KH * KW, Ho, Wo)
    gy = jnp.arange(Ho, dtype=data.dtype) * sh - ph_
    gx = jnp.arange(Wo, dtype=data.dtype) * sw - pw_
    base_y, base_x = jnp.meshgrid(gy, gx, indexing="ij")
    cols = []
    cg = C // dg
    for ky in range(KH):
        for kx in range(KW):
            tap = ky * KW + kx
            parts = []
            for g in range(dg):
                ys = base_y + ky * dh + off[:, g, tap, 0]
                xs = base_x + kx * dw + off[:, g, tap, 1]
                sub = data[:, g * cg:(g + 1) * cg]
                val = jax.vmap(_sample_one)(sub, xs, ys)
                if mask is not None:
                    val = val * mask[:, g, tap][:, None]
                parts.append(val)
            cols.append(jnp.concatenate(parts, axis=1))   # (B, C, Ho, Wo)
    col = jnp.stack(cols, axis=2)                         # (B, C, K*K, Ho, Wo)
    wmat = weight.reshape(O, C, KH * KW)
    out = jnp.einsum("bckhw,ock->bohw", col, wmat)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3),
                          stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                          num_filter=0, num_group=1, num_deformable_group=1,
                          no_bias=False, **_ignored):
    """Deformable conv v1 (reference contrib/deformable_convolution.cc):
    every kernel tap samples the input at a learned fractional offset.

    Expressed TPU-style as K*K bilinear gathers (piecewise-linear in the
    offsets, so JAX autodiff reproduces the reference's offset gradients)
    followed by one (C*K*K) x O matmul on the MXU.
    """
    return _deform_conv(data, offset, None, weight, bias, kernel, stride,
                        pad, dilate, num_group, num_deformable_group,
                        no_bias)


@register("_contrib_ModulatedDeformableConvolution",
          aliases=("ModulatedDeformableConvolution",))
def ModulatedDeformableConvolution(data, offset, mask, weight, bias=None,
                                   kernel=(3, 3), stride=(1, 1), pad=(0, 0),
                                   dilate=(1, 1), num_filter=0, num_group=1,
                                   num_deformable_group=1, no_bias=False,
                                   **_ignored):
    """Deformable conv v2 (reference
    contrib/modulated_deformable_convolution.cc): v1 plus a learned
    per-tap modulation mask (B, dg*K*K, Ho, Wo) multiplying each sampled
    value before the matmul."""
    return _deform_conv(data, offset, mask, weight, bias, kernel, stride,
                        pad, dilate, num_group, num_deformable_group,
                        no_bias)


@register("_contrib_mrcnn_mask_target", aliases=("mrcnn_mask_target",),
          n_out=2, differentiable=False)
def mrcnn_mask_target(rois, gt_masks, matches, cls_targets, num_rois=0,
                      num_classes=0, mask_size=(14, 14), sample_ratio=2):
    """Mask-RCNN training target generator (reference
    contrib/mrcnn_mask_target.cu MRCNNMaskTargetKernel): RoIAligns each
    roi's matched ground-truth mask to ``mask_size`` (zero outside the
    image, average of sample_ratio^2 bilinear taps per bin) and emits a
    per-class one-hot weight volume.

    rois (B, N, 4) corner format, gt_masks (B, M, H, W), matches (B, N),
    cls_targets (B, N) -> (mask_targets, mask_cls) both (B, N, C, mh, mw).
    """
    B, N = rois.shape[:2]
    M, H, W = gt_masks.shape[1:]
    mh, mw = int(mask_size[0]), int(mask_size[1])
    C = int(num_classes)
    sr = int(sample_ratio)
    if sr <= 0:
        raise NotImplementedError(
            "sample_ratio=-1 (adaptive grid) is data-dependent; use a "
            "positive sampling ratio on TPU")

    def bilinear_zero(img, ys, xs):
        """ROIAlign bilinear with zero outside [-1, H] x [-1, W]
        (mrcnn_mask_target.cu bilinear_interpolate)."""
        valid = (ys >= -1.0) & (ys <= H) & (xs >= -1.0) & (xs <= W)
        y = jnp.clip(ys, 0.0, H - 1.0)
        x = jnp.clip(xs, 0.0, W - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        ly, lx = y - y0, x - x0
        v = ((1 - ly) * (1 - lx) * img[y0, x0] + (1 - ly) * lx * img[y0, x1]
             + ly * (1 - lx) * img[y1, x0] + ly * lx * img[y1, x1])
        return jnp.where(valid, v, 0.0)

    def one(roi, match, masks):
        x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bh, bw = rh / mh, rw / mw
        py = jnp.arange(mh, dtype=rois.dtype)
        px = jnp.arange(mw, dtype=rois.dtype)
        sy = (jnp.arange(sr, dtype=rois.dtype) + 0.5) / sr
        sx = (jnp.arange(sr, dtype=rois.dtype) + 0.5) / sr
        ys = y1 + (py[:, None, None, None] + sy[None, None, :, None]) * bh
        xs = x1 + (px[None, :, None, None] + sx[None, None, None, :]) * bw
        ys = jnp.broadcast_to(ys, (mh, mw, sr, sr)).reshape(-1)
        xs = jnp.broadcast_to(xs, (mh, mw, sr, sr)).reshape(-1)
        img = masks[match.astype(jnp.int32)]
        vals = bilinear_zero(img, ys, xs).reshape(mh, mw, sr * sr)
        return vals.mean(-1)

    sampled = jax.vmap(lambda rs, ms, masks: jax.vmap(
        lambda r, m: one(r, m, masks))(rs, ms))(rois, matches, gt_masks)
    mask_targets = jnp.broadcast_to(sampled[:, :, None], (B, N, C, mh, mw))
    cls_ids = jnp.arange(C, dtype=cls_targets.dtype)
    onehot = (cls_targets[..., None] == cls_ids).astype(gt_masks.dtype)
    mask_cls = jnp.broadcast_to(onehot[..., None, None], (B, N, C, mh, mw))
    return mask_targets, mask_cls
