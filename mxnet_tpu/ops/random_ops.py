"""Random sampling operators.

Role parity: reference ``src/operator/random/sample_op.cc`` (_random_*
fixed-parameter samplers + *_like variants) and
``src/operator/random/multisample_op.cc`` (_sample_*: per-row distribution
parameters). TPU-native: jax.random with keys bound at invoke time
(state_binders), so replay under the tape and tracing under jit are
deterministic — the role of the reference's per-op ResourceRequest
kRandom generator state.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import dtype_np
from ._common import _bind_key, _RNG, _dt  # noqa: F401
from .registry import register, register_alias








@register("_random_exponential", aliases=("random_exponential",),
          differentiable=False, state_binders=_RNG)
def _random_exponential(lam=1.0, shape=None, ctx=None, dtype=None, key=None):
    return jax.random.exponential(
        key, tuple(shape or ()), _dt(dtype)) / lam


@register("_random_exponential_like", differentiable=False,
          state_binders=_RNG)
def _random_exponential_like(data, lam=1.0, key=None):
    return jax.random.exponential(key, data.shape, data.dtype) / lam


@register("_random_gamma", aliases=("random_gamma",), differentiable=False,
          state_binders=_RNG)
def _random_gamma(alpha=1.0, beta=1.0, shape=None, ctx=None, dtype=None,
                  key=None):
    return jax.random.gamma(key, alpha, tuple(shape or ()), _dt(dtype)) * beta


@register("_random_gamma_like", differentiable=False, state_binders=_RNG)
def _random_gamma_like(data, alpha=1.0, beta=1.0, key=None):
    return jax.random.gamma(key, alpha, data.shape, data.dtype) * beta


@register("_random_poisson", aliases=("random_poisson",),
          differentiable=False, state_binders=_RNG)
def _random_poisson(lam=1.0, shape=None, ctx=None, dtype=None, key=None):
    return jax.random.poisson(key, lam, tuple(shape or ())).astype(_dt(dtype))


@register("_random_poisson_like", differentiable=False, state_binders=_RNG)
def _random_poisson_like(data, lam=1.0, key=None):
    return jax.random.poisson(key, lam, data.shape).astype(data.dtype)


@register("_random_negative_binomial", aliases=("random_negative_binomial",),
          differentiable=False, state_binders=_RNG)
def _random_negative_binomial(k=1, p=1.0, shape=None, ctx=None, dtype=None,
                              key=None):
    """NB(k, p) = Poisson(Gamma(k, (1-p)/p)) (reference sampler.h)."""
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, float(k), tuple(shape or ())) * ((1 - p) / p)
    return jax.random.poisson(kp, lam).astype(_dt(dtype))


@register("_random_negative_binomial_like", differentiable=False,
          state_binders=_RNG)
def _random_negative_binomial_like(data, k=1, p=1.0, key=None):
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, float(k), data.shape) * ((1 - p) / p)
    return jax.random.poisson(kp, lam).astype(data.dtype)


@register("_random_generalized_negative_binomial",
          aliases=("random_generalized_negative_binomial",),
          differentiable=False, state_binders=_RNG)
def _random_gnb(mu=1.0, alpha=1.0, shape=None, ctx=None, dtype=None,
                key=None):
    """GNB(mu, alpha): Poisson with Gamma(1/alpha, mu*alpha) rate."""
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, 1.0 / alpha, tuple(shape or ())) * (mu * alpha)
    return jax.random.poisson(kp, lam).astype(_dt(dtype))


@register("_random_generalized_negative_binomial_like",
          differentiable=False, state_binders=_RNG)
def _random_gnb_like(data, mu=1.0, alpha=1.0, key=None):
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, 1.0 / alpha, data.shape) * (mu * alpha)
    return jax.random.poisson(kp, lam).astype(data.dtype)


@register("_random_randint", aliases=("random_randint",),
          differentiable=False, state_binders=_RNG)
def _random_randint(low=0, high=1, shape=None, ctx=None, dtype=None,
                    key=None):
    return jax.random.randint(key, tuple(shape or ()), int(low), int(high),
                              _dt(dtype, _np.int32))


@register("_random_uniform_like", differentiable=False, state_binders=_RNG)
def _random_uniform_like(data, low=0.0, high=1.0, key=None):
    return jax.random.uniform(key, data.shape, data.dtype, low, high)


@register("_random_normal_like", differentiable=False, state_binders=_RNG)
def _random_normal_like(data, loc=0.0, scale=1.0, key=None):
    return loc + scale * jax.random.normal(key, data.shape, data.dtype)


register_alias("_random_uniform", "random_uniform", "uniform")
register_alias("_random_normal", "random_normal", "normal")


# ---- _sample_*: per-row distribution parameters (multisample_op.cc) ----

def _row_shape(param, shape):
    shape = tuple(shape or ())
    return param.shape + shape


@register("_sample_exponential", differentiable=False, state_binders=_RNG)
def _sample_exponential(lam, shape=None, dtype=None, key=None):
    out = jax.random.exponential(key, _row_shape(lam, shape), _dt(dtype))
    return out / lam.reshape(lam.shape + (1,) * (out.ndim - lam.ndim))


@register("_sample_gamma", differentiable=False, state_binders=_RNG)
def _sample_gamma(alpha, beta, shape=None, dtype=None, key=None):
    a = alpha.reshape(alpha.shape + (1,) * len(tuple(shape or ())))
    out = jax.random.gamma(key, a, _row_shape(alpha, shape), _dt(dtype))
    return out * beta.reshape(beta.shape + (1,) * (out.ndim - beta.ndim))


@register("_sample_poisson", differentiable=False, state_binders=_RNG)
def _sample_poisson(lam, shape=None, dtype=None, key=None):
    l = lam.reshape(lam.shape + (1,) * len(tuple(shape or ())))
    return jax.random.poisson(key, l, _row_shape(lam, shape)).astype(
        _dt(dtype))


@register("_sample_negative_binomial", differentiable=False,
          state_binders=_RNG)
def _sample_negative_binomial(k, p, shape=None, dtype=None, key=None):
    kg, kp = jax.random.split(key)
    ext = (1,) * len(tuple(shape or ()))
    kk = k.reshape(k.shape + ext).astype(jnp.float32)
    pp = p.reshape(p.shape + ext)
    lam = jax.random.gamma(kg, kk, _row_shape(k, shape)) * ((1 - pp) / pp)
    return jax.random.poisson(kp, lam).astype(_dt(dtype))


@register("_sample_generalized_negative_binomial", differentiable=False,
          state_binders=_RNG)
def _sample_gnb(mu, alpha, shape=None, dtype=None, key=None):
    kg, kp = jax.random.split(key)
    ext = (1,) * len(tuple(shape or ()))
    m = mu.reshape(mu.shape + ext)
    a = alpha.reshape(alpha.shape + ext)
    lam = jax.random.gamma(kg, 1.0 / a, _row_shape(mu, shape)) * (m * a)
    return jax.random.poisson(kp, lam).astype(_dt(dtype))


@register("_sample_multinomial", aliases=("sample_multinomial",),
          differentiable=False, state_binders=_RNG)
def _sample_multinomial(data, shape=None, get_prob=False, dtype=None,
                        key=None):
    """Categorical sampling from probability rows (reference
    sample_multinomial_op.cc). shape = number of draws per row."""
    n = 1
    if shape:
        n = int(shape[0] if isinstance(shape, (list, tuple)) else shape)
    logits = jnp.log(jnp.maximum(data, 1e-30))
    batch = data.shape[:-1]
    # jax.random.categorical wants the batch shape trailing in `shape`
    out = jax.random.categorical(key, logits, axis=-1, shape=(n,) + batch)
    out = jnp.moveaxis(out, 0, -1)          # -> batch + (n,)
    if not shape:
        out = out.reshape(batch)
    out = out.astype(_dt(dtype, _np.int32))
    if get_prob:
        idx = out.reshape(batch + (n,)).astype(jnp.int64)
        p = jnp.take_along_axis(logits, idx, axis=-1)
        if not shape:
            p = p.reshape(batch)
        return out, p
    return out


@register("_sample_unique_zipfian", differentiable=False, n_out=2,
          state_binders=_RNG)
def _sample_unique_zipfian(range_max=1, shape=None, key=None):
    """Approximate unique zipfian sampling (reference
    sample_op.cc SampleUniqueZipfian — used by contrib sparse embedding
    negative sampling). Draws with log-uniform (zipf-like) distribution,
    deduplicates per row."""
    shape = tuple(shape or (1,))
    u = jax.random.uniform(key, shape)
    draws = jnp.exp(u * jnp.log(float(range_max))).astype(jnp.int64) % \
        int(range_max)
    # count of unique draws per row (trials actually used)
    def row_unique(row):
        srt = jnp.sort(row)
        uniq = jnp.concatenate([jnp.array([1], srt.dtype),
                                (srt[1:] != srt[:-1]).astype(srt.dtype)])
        return uniq.sum()
    counts = jax.vmap(row_unique)(draws.reshape(-1, shape[-1]))
    return draws, counts.reshape(shape[:-1] + (1,) if len(shape) > 1
                                 else (1,)).astype(jnp.int64)


# ---- probability-density ops (reference src/operator/random/pdf_op.cc:
# _random_pdf_<distr>, differentiable w.r.t. sample AND distribution
# parameters — here jax autodiff instead of the hand-written *_Grad
# kernels in pdf_op.h) -----------------------------------------------------

from jax.scipy.special import gammaln as _gammaln


def _pexp(lpdf, is_log):
    return lpdf if is_log else jnp.exp(lpdf)


def _nb_lpdf(sample, k, p):
    """Shared NB log-pmf: lgamma(x+k) - lgamma(x+1) - lgamma(k)
    + k*log(p) + x*log(1-p) (pdf_op.h PDF_NegativeBinomial::LPDF)."""
    return (_gammaln(sample + k) - _gammaln(sample + 1) - _gammaln(k)
            + k * jnp.log(p) + sample * jnp.log(1 - p))


@register("_random_pdf_uniform", aliases=("random_pdf_uniform",))
def _random_pdf_uniform(sample, low, high, is_log=False):
    """PDF of U(low, high) at sample (pdf_op.h PDF_Uniform). Parameter
    arrays have one fewer trailing dim than ``sample``."""
    l, h = low[..., None], high[..., None]
    lpdf = -jnp.log(h - l) * jnp.ones_like(sample)
    return _pexp(lpdf, is_log)


@register("_random_pdf_normal", aliases=("random_pdf_normal",))
def _random_pdf_normal(sample, mu, sigma, is_log=False):
    """PDF of N(mu, sigma) (pdf_op.h PDF_Normal)."""
    u, s = mu[..., None], sigma[..., None]
    expo = -0.5 * (sample - u) ** 2 / (s * s)
    lpdf = expo - jnp.log(jnp.sqrt(2.0 * jnp.pi) * s)
    return _pexp(lpdf, is_log)


@register("_random_pdf_gamma", aliases=("random_pdf_gamma",))
def _random_pdf_gamma(sample, alpha, beta, is_log=False):
    """PDF of Gamma(shape=alpha, rate=beta) (pdf_op.h PDF_Gamma:
    a*log(b) + (a-1)*log(x) - b*x - lgamma(a))."""
    a, b = alpha[..., None], beta[..., None]
    lpdf = a * jnp.log(b) + (a - 1) * jnp.log(sample) - b * sample \
        - _gammaln(a)
    return _pexp(lpdf, is_log)


@register("_random_pdf_exponential", aliases=("random_pdf_exponential",))
def _random_pdf_exponential(sample, lam, is_log=False):
    """PDF of Exp(lam) (pdf_op.h PDF_Exponential)."""
    l = lam[..., None]
    lpdf = jnp.log(l) - l * sample
    return _pexp(lpdf, is_log)


@register("_random_pdf_poisson", aliases=("random_pdf_poisson",))
def _random_pdf_poisson(sample, lam, is_log=False):
    """PMF of Poisson(lam) (pdf_op.h PDF_Poisson)."""
    l = lam[..., None]
    lpdf = sample * jnp.log(l) - _gammaln(sample + 1) - l
    return _pexp(lpdf, is_log)


@register("_random_pdf_negative_binomial",
          aliases=("random_pdf_negative_binomial",))
def _random_pdf_negative_binomial(sample, k, p, is_log=False):
    """PMF of NB(k, p) (pdf_op.h PDF_NegativeBinomial)."""
    lpdf = _nb_lpdf(sample, k[..., None], p[..., None])
    return _pexp(lpdf, is_log)


@register("_random_pdf_generalized_negative_binomial",
          aliases=("random_pdf_generalized_negative_binomial",))
def _random_pdf_generalized_negative_binomial(sample, mu, alpha,
                                              is_log=False):
    """PMF of GNB(mu, alpha): NB with k=1/alpha, p=1/(mu*alpha+1)
    (pdf_op.h PDF_GeneralizedNegativeBinomial)."""
    kk = 1.0 / alpha[..., None]
    pp = 1.0 / (mu[..., None] * alpha[..., None] + 1.0)
    lpdf = _nb_lpdf(sample, kk, pp)
    return _pexp(lpdf, is_log)


@register("_random_pdf_dirichlet", aliases=("random_pdf_dirichlet",))
def _random_pdf_dirichlet(sample, alpha, is_log=False):
    """PDF of Dirichlet(alpha): sample (..., n, k), alpha (..., k) ->
    out (..., n) (pdf_op.h PDF_Dirichlet)."""
    a = alpha[..., None, :]
    lpdf = (jnp.sum((a - 1) * jnp.log(sample), axis=-1)
            + _gammaln(jnp.sum(a, axis=-1))
            - jnp.sum(_gammaln(a), axis=-1))
    return _pexp(lpdf, is_log)
