"""CTC loss: log-space alpha recursion as one lax.scan.

Role parity: reference ``src/operator/nn/ctc_loss.cc`` (Baidu warp-ctc,
vendored headers in `3rdparty/ctc_include/`). TPU-native: the forward
algorithm is a dense dynamic program over the extended label lattice —
expressed as ``lax.scan`` over time with vectorized batch/state axes, it
compiles to one fused XLA loop; the gradient falls out of autodiff through
the scan (warp-ctc hand-codes the beta recursion instead).

Convention matches Gluon's CTCLoss (reference `python/mxnet/gluon/loss.py`
CTCLoss): the *last* class index is blank; label padding may be any value
when ``label_lengths`` is given, otherwise labels < 0 mark padding.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

NEG_INF = -1e30


def ctc_loss(pred, labels, pred_lengths=None, label_lengths=None):
    """pred: (T, B, C) unnormalized activations; labels: (B, L) int.

    Returns per-example negative log likelihood, shape (B,).
    """
    T, B, C = pred.shape
    L = labels.shape[1]
    S = 2 * L + 1
    blank = C - 1

    logp = jax.nn.log_softmax(pred.astype(jnp.float32), axis=-1)
    labels = labels.astype(jnp.int32)

    if pred_lengths is None:
        pred_lengths = jnp.full((B,), T, dtype=jnp.int32)
    else:
        pred_lengths = pred_lengths.astype(jnp.int32)
    if label_lengths is None:
        label_lengths = jnp.sum((labels >= 0).astype(jnp.int32), axis=1)
    else:
        label_lengths = label_lengths.astype(jnp.int32)

    # extended sequence [blank, l1, blank, l2, ..., blank]: (B, S)
    ext = jnp.full((B, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(labels < 0, blank, labels))

    # transition mask: can we skip from s-2 to s?
    # allowed when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate(
        [jnp.full((B, 2), -1, dtype=jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)

    # states beyond 2*label_len+1 are invalid
    s_idx = jnp.arange(S)[None, :]
    valid = s_idx < (2 * label_lengths + 1)[:, None]

    alpha0 = jnp.full((B, S), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(B), blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_lengths > 0,
                  logp[0, jnp.arange(B), ext[:, 1]], NEG_INF))
    alpha0 = jnp.where(valid, alpha0, NEG_INF)

    def step(alpha, t):
        a_m1 = jnp.concatenate(
            [jnp.full((B, 1), NEG_INF), alpha[:, :-1]], axis=1)
        a_m2 = jnp.concatenate(
            [jnp.full((B, 2), NEG_INF), alpha[:, :-2]], axis=1)
        a_m2 = jnp.where(can_skip, a_m2, NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a_m1), a_m2)
        emit = jnp.take_along_axis(logp[t], ext, axis=1)
        new = merged + emit
        new = jnp.where(valid, new, NEG_INF)
        # frozen past pred_lengths: carry alpha unchanged
        active = (t < pred_lengths)[:, None]
        new = jnp.where(active, new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))

    # final: logaddexp of last two valid states
    b_idx = jnp.arange(B)
    sl = 2 * label_lengths  # index of final blank
    last_blank = alpha[b_idx, sl]
    last_label = jnp.where(label_lengths > 0,
                           alpha[b_idx, jnp.maximum(sl - 1, 0)], NEG_INF)
    ll = jnp.logaddexp(last_blank, last_label)
    return -ll


@register("_ctc_loss", aliases=("ctc_loss", "CTCLoss_op", "_contrib_ctc_loss"))
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False,
              blank_label="last"):
    """Op wrapper: data (T, B, C) — see module docstring."""
    return ctc_loss(data, label,
                    None if data_lengths is None else data_lengths,
                    None if label_lengths is None else label_lengths)
