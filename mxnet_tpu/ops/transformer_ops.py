"""Transformer acceleration ops: interleaved-projection attention matmuls.

Role parity: reference ``src/operator/contrib/transformer.cc`` /
``transformer.cu`` (``_contrib_interleaved_matmul_selfatt_qk`` etc.), the
ops GluonNLP's BERT uses to fuse multi-head attention projections into
strided batched gemms. TPU-native: each op is a single ``jnp.einsum`` over
the interleaved layout — XLA lowers it to one batched MXU matmul, which is
exactly the role the reference's cuBLAS strided-batch calls play.

Layout (from the reference kernels' stride math): the projected last dim of
``queries_keys_values`` is ordered ``(heads, 3, head_dim)`` — for every head
a contiguous [q|k|v] block — and attention batches are sequence-major,
head-minor: attention row ``b*heads + h``.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from .registry import register

__all__ = [
    "interleaved_matmul_selfatt_qk",
    "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk",
    "interleaved_matmul_encdec_valatt",
]


@register("_contrib_interleaved_matmul_selfatt_qk",
          aliases=("interleaved_matmul_selfatt_qk",))
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """scaled Q @ K^T from an interleaved self-attention projection.

    queries_keys_values: (seq, batch, 3*heads*head_dim) with per-head
    contiguous [q|k|v]. Returns (batch*heads, seq, seq) scores scaled by
    1/sqrt(head_dim) (reference transformer.cu scale).
    """
    S, B, P = queries_keys_values.shape
    D = P // (3 * heads)
    qkv = queries_keys_values.reshape(S, B, heads, 3, D)
    q, k = qkv[..., 0, :], qkv[..., 1, :]
    scale = jnp.asarray(1.0 / math.sqrt(D), q.dtype)
    att = jnp.einsum("qbhd,kbhd->bhqk", q * scale, k)
    return att.reshape(B * heads, S, S)


@register("_contrib_interleaved_matmul_selfatt_valatt",
          aliases=("interleaved_matmul_selfatt_valatt",))
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads=1):
    """attention @ V, re-assembled to the (seq, batch, embed) layout.

    attention: (batch*heads, seq, seq); output (seq, batch, heads*head_dim).
    """
    S, B, P = queries_keys_values.shape
    D = P // (3 * heads)
    v = queries_keys_values.reshape(S, B, heads, 3, D)[..., 2, :]
    att = attention.reshape(B, heads, S, S)
    out = jnp.einsum("bhqk,kbhd->qbhd", att, v)
    return out.reshape(S, B, heads * D)


@register("_contrib_interleaved_matmul_encdec_qk",
          aliases=("interleaved_matmul_encdec_qk",))
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Cross-attention scores: separate query tensor, interleaved [k|v].

    queries: (q_seq, batch, heads*head_dim);
    keys_values: (kv_seq, batch, 2*heads*head_dim).
    Returns (batch*heads, q_seq, kv_seq) scaled by 1/sqrt(head_dim).
    """
    Sq, B, E = queries.shape
    D = E // heads
    Sk = keys_values.shape[0]
    q = queries.reshape(Sq, B, heads, D)
    k = keys_values.reshape(Sk, B, heads, 2, D)[..., 0, :]
    scale = jnp.asarray(1.0 / math.sqrt(D), q.dtype)
    att = jnp.einsum("qbhd,kbhd->bhqk", q * scale, k)
    return att.reshape(B * heads, Sq, Sk)


@register("_contrib_interleaved_matmul_encdec_valatt",
          aliases=("interleaved_matmul_encdec_valatt",))
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    """Cross-attention context: attention @ V from interleaved [k|v].

    keys_values: (kv_seq, batch, 2*heads*head_dim);
    attention: (batch*heads, q_seq, kv_seq).
    Returns (q_seq, batch, heads*head_dim).
    """
    Sk, B, P = keys_values.shape
    D = P // (2 * heads)
    v = keys_values.reshape(Sk, B, heads, 2, D)[..., 1, :]
    Sq = attention.shape[1]
    att = attention.reshape(B, heads, Sq, Sk)
    out = jnp.einsum("bhqk,kbhd->qbhd", att, v)
    return out.reshape(Sq, B, heads * D)
