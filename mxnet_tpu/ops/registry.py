"""Operator registry and eager/traced dispatch.

Role parity: the nnvm op registry + attr functors
(reference `include/mxnet/op_attr_types.h:217-331`: FCompute, FInferShape...)
and the imperative dispatch path (`src/imperative/imperative.cc:89` Invoke →
`imperative_utils.h:395` PushFCompute → Engine::PushAsync).

TPU-native design: an op is ONE pure JAX function. Shape/type inference,
kernel selection, fusion, and async scheduling are all delegated to
XLA — eager calls dispatch asynchronously via JAX (the role of the reference
dependency engine `src/engine/threaded_engine.h:282` is played by XLA's
program order + JAX async dispatch), and the same function is traceable under
``jax.jit`` so hybridized graphs compile to a single HLO module (the role of
CachedOp `src/imperative/cached_op.cc:1023`).

Gradients come from ``jax.vjp`` over the recorded tape — no per-op backward
registration (the role of nnvm's FGradient) is needed.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

from .. import _tape
from .. import engine as _engine

__all__ = ["Op", "register", "get_op", "list_ops", "invoke", "wrap_out"]

_OP_REGISTRY: Dict[str, "Op"] = {}


class Op:
    """Registered operator: a named pure JAX function with metadata.

    ``state_binders`` maps kwarg names to zero-arg callables resolved at
    *invoke* time (not at replay/backward time): RNG keys and the
    train-mode flag are captured into the recorded kwargs so tape replay
    is deterministic — the reference gets the same property from stateful
    cuDNN dropout descriptors held by the op state
    (`src/operator/nn/dropout-inl.h`)."""
    __slots__ = ("name", "fn", "n_out", "aliases", "doc", "namespace",
                 "differentiable", "state_binders", "host_op")

    def __init__(self, name, fn, n_out=1, aliases=(), doc=None,
                 namespace="nd", differentiable=True, state_binders=None,
                 host_op=False):
        self.name = name
        self.fn = fn
        self.n_out = n_out
        self.aliases = aliases
        self.doc = doc or fn.__doc__
        self.namespace = namespace
        self.differentiable = differentiable
        self.state_binders = state_binders or {}
        self.host_op = host_op

    def __call__(self, *args, **kwargs):
        return invoke(self, *args, **kwargs)

    def __repr__(self):
        return "<Op %s>" % self.name


def register(name=None, n_out=1, aliases=(), namespace="nd",
             differentiable=True, state_binders=None, host_op=False):
    """Decorator: register a pure JAX function as a framework op.

    ``host_op=True`` registers an eager host-side function (the reference's
    CPU-only FComputeEx kernels, e.g. the DGL graph samplers): invoke
    passes NDArray/CSRNDArray objects through unmodified and records no
    tape — these never appear inside a jitted program."""
    def deco(fn):
        opname = name or fn.__name__
        # duplicate registration is fatal (reference nnvm registry CHECKs):
        # a silent override would shadow an op with different semantics
        for n in (opname,) + tuple(aliases):
            if n in _OP_REGISTRY:
                raise ValueError(
                    "operator %r is already registered (by %r); use "
                    "register_alias to re-expose an existing op"
                    % (n, _OP_REGISTRY[n].name))
        op = Op(opname, fn, n_out=n_out, aliases=aliases,
                namespace=namespace, differentiable=differentiable,
                state_binders=state_binders, host_op=host_op)
        _OP_REGISTRY[opname] = op
        for a in aliases:
            _OP_REGISTRY[a] = op
        return op
    return deco


def get_op(name: str) -> Optional[Op]:
    return _OP_REGISTRY.get(name)


def register_alias(existing: str, *names: str):
    """Expose an already-registered op under additional names (the
    reference's .add_alias, e.g. `_npi_add` -> add)."""
    op = _OP_REGISTRY[existing]
    for n in names:
        _OP_REGISTRY[n] = op
    return op


def list_ops():
    """Parity with MXListAllOpNames (reference `src/c_api/c_api.cc`)."""
    return sorted(_OP_REGISTRY.keys())


def wrap_out(val, like=None):
    """Wrap a raw jax value into an NDArray in the current context."""
    from ..ndarray.ndarray import NDArray
    ctx = like.ctx if like is not None else None
    return NDArray(val, ctx=ctx)


def invoke(op: Op, *args, out=None, **kwargs):
    """Eager-dispatch an op: unwrap handles → pure fn → wrap → record.

    Under jax tracing (inside CachedOp/jit) the same path runs with tracers
    in ``_data`` — no separate symbolic executor is needed.
    """
    from ..ndarray.ndarray import NDArray

    if op.host_op:
        return op.fn(*args, **kwargs)

    vals = []
    nd_inputs = []
    parents = []
    for a in args:
        if isinstance(a, NDArray):
            vals.append(a._data)
            nd_inputs.append(a)
            node = a._ag_node
            if node is None:
                parents.append(_tape.Const(a._data))
            else:
                parents.append(node if isinstance(node, tuple) else (node, 0))
        else:
            vals.append(a)
            parents.append(_tape.Const(a))

    # tensor-valued keyword args (masks, index arrays) unwrap too; they are
    # treated as constants w.r.t. the tape (positional args carry gradients)
    for k, v in kwargs.items():
        if isinstance(v, NDArray):
            kwargs[k] = v._data

    for kname, binder in op.state_binders.items():
        if kname not in kwargs:
            kwargs[kname] = binder()

    out_vals = op.fn(*vals, **kwargs)
    multi = isinstance(out_vals, tuple)
    outs = out_vals if multi else (out_vals,)

    if _engine.is_naive():
        # NaiveEngine semantics (reference src/engine/naive_engine.cc):
        # serialize dispatch so device-side failures surface inside the
        # calling statement instead of at the next sync point. Tracers have
        # no block_until_ready, so tracing is unaffected.
        for v in outs:
            if hasattr(v, "block_until_ready"):
                v.block_until_ready()

    recording = op.differentiable and _tape.is_recording()

    node = None
    if recording:
        node = _tape.OpNode(op.fn, parents, len(outs), dict(kwargs), op.name)

    results = []
    out_list = out if isinstance(out, (list, tuple)) else ([out] if out is not None else None)
    for i, v in enumerate(outs):
        if out_list is not None and i < len(out_list) and out_list[i] is not None:
            tgt = out_list[i]
            tgt._data = v
            tgt._ag_node = (node, i) if node is not None else None
            results.append(tgt)
        else:
            arr = NDArray(v, ctx=nd_inputs[0].ctx if nd_inputs else None)
            if node is not None:
                arr._ag_node = (node, i)
            results.append(arr)
    if multi:
        return tuple(results)
    return results[0]
