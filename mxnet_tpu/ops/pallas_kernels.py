"""Pallas TPU kernels for the hot ops.

Role parity: the reference hand-writes CUDA kernels for its hot paths
(`src/operator/nn/` .cu files, fusion RTC `src/operator/fusion/`); here the
few ops XLA doesn't already fuse optimally get Pallas kernels. First
citizen: flash attention — O(S) memory blockwise attention with online
softmax, the kernel that sets the ceiling for long-context transformer
throughput. Forward is Pallas (MXU matmuls over VMEM-resident tiles,
fp32 accumulators); backward uses XLA's autodiff over the reference
formulation (recompute-based, still O(S^2/block) flops but memory-safe via
jax.checkpoint).

Layout: (batch, heads, seq, head_dim), blocks of 128 on seq to match the
MXU/VPU tiling constraints (pallas_guide.md).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["flash_attention", "pallas_available", "flash_attention_usable"]

BLOCK_Q = 128
BLOCK_K = 128
NEG_INF = -1e30


def pallas_available():
    return _HAS_PALLAS


def flash_attention_usable(q_shape, causal=False):
    """Whether the pallas path supports this problem size."""
    if not _HAS_PALLAS:
        return False
    B, H, S, D = q_shape
    return S % BLOCK_Q == 0 and S >= BLOCK_Q and D <= 256


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, blk_q, blk_k,
                 seq_len):
    """One (batch*head, q-block) program: stream K/V blocks with online
    softmax accumulation in fp32."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)  # (blk_q, D)

    n_kb = seq_len // blk_k

    def body(kb, carry):
        acc, m_i, l_i = carry
        k = k_ref[0, pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_i - m_new)
        l_new = l_i * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc = acc * corr[:, None] + pv
        return acc, m_new, l_new

    D = q.shape[-1]
    acc = jnp.zeros((blk_q, D), jnp.float32)
    m_i = jnp.full((blk_q,), jnp.float32(NEG_INF), jnp.float32)
    l_i = jnp.zeros((blk_q,), jnp.float32)
    if causal:
        # only blocks up to (and including) the diagonal contribute
        n_iter = qi * (blk_q // blk_k) + (blk_q // blk_k)
    else:
        n_iter = n_kb
    # int32 loop bounds: under x64 a Python-int bound makes the induction
    # variable i64 and the `kb * blk_k` block-index arithmetic mixes
    # i64/i32 ('arith.muli' verification error in Mosaic)
    acc, m_i, l_i = jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_iter),
                                      body, (acc, m_i, l_i))
    o_ref[0] = (acc / jnp.maximum(l_i, jnp.float32(1e-20))[:, None]
                ).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, interpret):
    B, H, S, D = q.shape
    # plain Python float: np.float64 is strongly typed and would promote
    # the f32 kernel to f64 under x64 (TPU Mosaic has no 64-bit types)
    scale = float(1.0 / np.sqrt(D))
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)
    grid = (B * H, S // BLOCK_Q)
    kernel = functools.partial(_attn_kernel, scale=scale, causal=causal,
                               blk_q=BLOCK_Q, blk_k=BLOCK_K, seq_len=S)
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, D), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )
    # trace with x64 off: this framework enables jax_enable_x64 globally
    # (int64 index parity), but Mosaic's grid machinery then emits i64
    # scalars that fail to legalize ('func.return') on the TPU compiler —
    # the kernel itself is pure f32/i32
    from jax.experimental import enable_x64
    with enable_x64(False):
        out = call(qr, kr, vr)
    return out.reshape(B, H, S, D)


def _reference_attention(q, k, v, causal):
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(D)
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, interpret=False):
    """Blockwise exact attention, (B, H, S, D) layout."""
    return _flash_fwd(q, k, v, causal, interpret)


def _fa_fwd(q, k, v, causal, interpret):
    return _flash_fwd(q, k, v, causal, interpret), (q, k, v)


def _fa_bwd(causal, interpret, res, g):
    q, k, v = res
    # backward via XLA autodiff of the reference formulation with remat —
    # correct and memory-bounded; a hand-written pallas bwd is a further
    # optimization hook
    f = jax.checkpoint(lambda q, k, v: _reference_attention(q, k, v, causal))
    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
