"""Pallas TPU kernels for the hot ops.

Role parity: the reference hand-writes CUDA kernels for its hot paths
(`src/operator/nn/` .cu files, fusion RTC `src/operator/fusion/`); here the
few ops XLA doesn't already fuse optimally get Pallas kernels. First
citizen: flash attention — O(S) memory blockwise attention with online
softmax, the kernel that sets the ceiling for long-context transformer
throughput. This is exactly the fusion the reference could never do:
its attention was composed from ops (`src/operator/contrib/
transformer.cc`), materialising the (S, S) score matrix in HBM.

Forward AND backward are Pallas (MXU matmuls over VMEM-resident tiles,
fp32 accumulators; backward recomputes score tiles from the saved
logsumexp — the standard flash-attention-2 dq/dkdv split).

Supports the full training configuration of the transformer model zoo:
  - key padding mask (B, S): BERT-style bidirectional masking;
  - causal masking with block-level skipping;
  - attention dropout via a counter-based in-kernel PRNG (lowbias32 hash
    over global (head, q, k) element coordinates + a per-call seed), so
    forward and both backward kernels regenerate identical keep bits with
    no O(S^2) mask materialisation and no pltpu PRNG dependency (which
    has no CPU interpret path).

Layout: (batch, heads, seq, head_dim), blocks of 128 on seq to match the
MXU/VPU tiling constraints (pallas_guide.md).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["flash_attention", "flash_attention_bshd", "pallas_available",
           "flash_attention_usable", "flash_attention_bshd_usable"]

import os as _os

# 128 is the alignment unit (MXU/VPU tiling); actual blocks are chosen
# per call by _pick_blocks: the largest 128-multiple divisor of S up to
# the preferred size. Bigger k-blocks amortize the streaming loop's
# per-iteration overhead — measured on-chip (BERT-base s512): 128/128 =
# 51 TFLOP/s, 256/512 = 74 TFLOP/s end-to-end.
BLOCK_Q = 128
BLOCK_K = 128
_PREF_BLOCK_Q = int(_os.environ.get("MXTPU_FLASH_BLOCK_Q", "256"))
_PREF_BLOCK_K = int(_os.environ.get("MXTPU_FLASH_BLOCK_K", "512"))


def _pick_blocks(S, causal):
    """(blk_q, blk_k) for a length-S problem: largest 128-multiple
    divisors of S up to the preferred sizes. Causal block-skipping
    assumes blk_k <= blk_q, so clamp there. Dropout keep-bits are keyed
    on GLOBAL (head, q, k) coordinates, so block choice never changes
    the sampled mask."""
    def pick(pref):
        # round env-supplied preferences down to a positive multiple of
        # 128 first, else the divisor search below can't terminate
        pref = max(128, (int(pref) // 128) * 128)
        b = max(128, min(pref, S))
        while b > 128 and S % b:
            b -= 128
        return b
    bq = pick(_PREF_BLOCK_Q)
    bk = pick(_PREF_BLOCK_K)
    if causal and (bk > bq or bq % bk):
        # block-skip arithmetic needs blk_k to DIVIDE blk_q
        bk = bq
    return bq, bk


def _pick_blocks_bshd(S, causal, HD, itemsize):
    """Block sizes for the head-fused kernels, shrunk until the VMEM
    footprint fits. Worst case is the dkdv backward: two FULL (S, HD)
    operands + four block-sized operands, all double-buffered by the
    pipeline. Deterministic in (S, causal, HD, itemsize) so the forward
    and backward passes agree on blk_q (the saved-LSE layout depends on
    it)."""
    bq, bk = _pick_blocks(S, causal)
    budget = 14 * 1024 * 1024

    def fits(bq, bk):
        vmem = 2 * (2 * S + 4 * bk + bq) * HD * itemsize
        return vmem <= budget

    def shrink(b):
        b -= 128
        while b > 128 and S % b:
            b -= 128
        return max(b, 128)

    while bk > 128 and not fits(bq, bk):
        bk = shrink(bk)
    while bq > 128 and not fits(bq, bk):
        bq = shrink(bq)
    if causal and (bk > bq or bq % bk):
        # the VMEM shrink can break the blk_k-divides-blk_q invariant the
        # causal block-skip arithmetic (n_iter = (qi+1)*(blk_q//blk_k))
        # relies on; restore it with the largest 128-multiple divisor of bq
        # no bigger than the budget-respecting bk (128 always qualifies)
        cap = min(bq, bk)
        bk = 128
        for cand in range(cap, 127, -128):
            if bq % cand == 0:
                bk = cand
                break
    return bq, bk
NEG_INF = -1e30


def pallas_available():
    return _HAS_PALLAS


def flash_attention_usable(q_shape, causal=False):
    """Whether the pallas path supports this problem size."""
    if not _HAS_PALLAS:
        return False
    B, H, S, D = q_shape
    return S % BLOCK_Q == 0 and S >= BLOCK_Q and D <= 256


# --------------------------------------------------------------- dropout rng

_U32 = jnp.uint32


def _lowbias32(x):
    """lowbias32 integer hash (public-domain constant set): good avalanche
    at 2 multiply + 3 xorshift — plenty for dropout bits, runs on the VPU
    as plain uint32 lane math."""
    x = x ^ (x >> _U32(16))
    x = x * _U32(0x7FEB352D)
    x = x ^ (x >> _U32(15))
    x = x * _U32(0x846CA68B)
    x = x ^ (x >> _U32(16))
    return x


def _keep_bits(seed, bh, q0, k0, blk_q, blk_k, keep_prob):
    """Deterministic keep-mask tile for global element (bh, q0+i, k0+j).

    Identical calls from the forward and the two backward kernels
    regenerate identical bits — the dropout mask is never materialised.
    """
    qi = q0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    ki = k0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    c = (qi.astype(_U32) * _U32(0x9E3779B9)) ^ \
        (ki.astype(_U32) * _U32(0x85EBCA6B)) ^ \
        (bh.astype(_U32) * _U32(0xC2B2AE35)) ^ seed.astype(_U32)
    bits = _lowbias32(c)
    thresh = _U32(min(int(keep_prob * 4294967296.0), 4294967295))
    return bits < thresh


# ----------------------------------------------------------- shared tile math

def _tile_dead(causal, q0, k0, blk_q, blk_k, mask_row):
    """Combined causal/key-padding invalid-position mask for one tile
    (None when every position is live)."""
    dead = None
    if causal:
        q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        dead = q_pos < k_pos
    if mask_row is not None:
        mdead = mask_row == 0
        dead = mdead if dead is None else (dead | mdead)
    return dead


def _fwd_tile_update(q, k, v, carry, dead, seed, bh, q0, k0, blk_q, blk_k,
                     dropout, scale):
    """One online-softmax accumulation step over a (q-block, k-block)
    tile — the single implementation both the BHSD and the head-fused
    BSHD forward kernels run. Masked positions contribute EXACTLY zero
    (not exp(-1e30 - m)): fully-masked rows keep l = 0 and the epsilon
    guard at the end returns 0 output instead of garbage. The normalizer
    l accumulates PRE-dropout probabilities (dropout rescales P, never
    the softmax denominator)."""
    acc, m_i, l_i = carry
    # matmuls run in the OPERAND dtype (bf16 inputs ride the fast MXU
    # path, 3x the f32 rate) with f32 accumulation; all softmax math
    # stays f32. k/v follow q's dtype so partially-AMP'd models with
    # mixed q/k/v precisions still trace (dot_general requires equal
    # operand dtypes).
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    if dead is not None:
        s = jnp.where(dead, jnp.float32(NEG_INF), s)
    m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[:, None])
    if dead is not None:
        p = jnp.where(dead, jnp.float32(0.0), p)
    corr = jnp.exp(m_i - m_new)
    l_new = l_i * corr + jnp.sum(p, axis=-1)
    if dropout > 0.0:
        keep = _keep_bits(seed, bh, q0, k0, blk_q, blk_k, 1.0 - dropout)
        p = jnp.where(keep, p / jnp.float32(1.0 - dropout),
                      jnp.float32(0.0))
    pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return p, (acc * corr[:, None] + pv, m_new, l_new)


def _bwd_tile_ds(q, k, v, do, lse, delta, mask_row, causal, dropout,
                 scale, seed, bh, q0, k0, blk_q, blk_k):
    """Recompute dS = P o (dP - delta) for one tile (and Pdrop for dV) —
    the single implementation all four backward kernels run."""
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    do = do.astype(q.dtype)
    p, pd, keep = _recompute_tile(q, k, lse, seed, bh, q0, k0, mask_row,
                                  causal, dropout, scale, blk_q, blk_k)
    dpd = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    if dropout > 0.0:
        dp = jnp.where(keep, dpd / jnp.float32(1.0 - dropout),
                       jnp.float32(0.0))
    else:
        dp = dpd
    ds = p * (dp - delta[:, None])
    return ds, pd


# ------------------------------------------------------------------- forward

def _attn_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, o_ref,
                     lse_ref, *, scale, causal, blk_q, blk_k, seq_len,
                     dropout, has_mask):
    """One (batch*head, q-block) program: stream K/V blocks with online
    softmax accumulation in fp32. Also writes the per-row logsumexp the
    backward kernels recompute probability tiles from."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    seed = seed_ref[0, 0]
    q = q_ref[0]                                  # (blk_q, D), raw dtype

    n_kb = seq_len // blk_k

    def body(kb, carry):
        k = k_ref[0, pl.ds(kb * blk_k, blk_k), :]
        v = v_ref[0, pl.ds(kb * blk_k, blk_k), :]
        mrow = mask_ref[0, 0:1, pl.ds(kb * blk_k, blk_k)] \
            if has_mask else None
        dead = _tile_dead(causal, qi * blk_q, kb * blk_k, blk_q, blk_k,
                          mrow)
        _, carry = _fwd_tile_update(q, k, v, carry, dead, seed, bh,
                                    qi * blk_q, kb * blk_k, blk_q, blk_k,
                                    dropout, scale)
        return carry

    D = q.shape[-1]
    acc = jnp.zeros((blk_q, D), jnp.float32)
    m_i = jnp.full((blk_q,), jnp.float32(NEG_INF), jnp.float32)
    l_i = jnp.zeros((blk_q,), jnp.float32)
    if causal:
        # only blocks up to (and including) the diagonal contribute
        n_iter = qi * (blk_q // blk_k) + (blk_q // blk_k)
    else:
        n_iter = n_kb
    # int32 loop bounds: under x64 a Python-int bound makes the induction
    # variable i64 and the `kb * blk_k` block-index arithmetic mixes
    # i64/i32 ('arith.muli' verification error in Mosaic)
    acc, m_i, l_i = jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_iter),
                                      body, (acc, m_i, l_i))
    l_safe = jnp.maximum(l_i, jnp.float32(1e-20))
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, :] = m_i + jnp.log(l_safe)


# ------------------------------------------------------------ backward tiles

def _recompute_tile(q, k, lse, seed, bh, q0, k0, mask_row, causal,
                    dropout, scale, blk_q, blk_k):
    """Recompute (P, Pdrop, keep, dead) for one (q-block, k-block) tile
    from the saved logsumexp. Shared by the dq and dkdv kernels."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    dead = None
    if causal:
        q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        dead = q_pos < k_pos
    if mask_row is not None:
        mdead = mask_row == 0
        dead = mdead if dead is None else (dead | mdead)
    p = jnp.exp(s - lse[:, None])
    if dead is not None:
        p = jnp.where(dead, jnp.float32(0.0), p)
    keep = None
    pd = p
    if dropout > 0.0:
        keep = _keep_bits(seed, bh, q0, k0, blk_q, blk_k, 1.0 - dropout)
        pd = jnp.where(keep, p / jnp.float32(1.0 - dropout),
                       jnp.float32(0.0))
    return p, pd, keep


def _attn_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, mask_ref, dq_ref, *, scale, causal,
                        blk_q, blk_k, seq_len, dropout, has_mask):
    """grad wrt Q: one (batch*head, q-block) program streaming K blocks.
    dS = P o (dP - delta); dQ = dS K * scale (flash-attention-2 eq. 4)."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    seed = seed_ref[0, 0]
    q = q_ref[0]
    do = do_ref[0]                               # (blk_q, D)
    lse = lse_ref[0, 0, :]                       # (blk_q,)
    delta = delta_ref[0, 0, :]                   # (blk_q,)

    def body(kb, dq_acc):
        k = k_ref[0, pl.ds(kb * blk_k, blk_k), :]
        v = v_ref[0, pl.ds(kb * blk_k, blk_k), :]
        mask_row = None
        if has_mask:
            mask_row = mask_ref[0, 0:1, pl.ds(kb * blk_k, blk_k)]
        ds, _ = _bwd_tile_ds(q, k, v, do, lse, delta, mask_row, causal,
                             dropout, scale, seed, bh, qi * blk_q,
                             kb * blk_k, blk_q, blk_k)
        return dq_acc + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        n_iter = qi * (blk_q // blk_k) + (blk_q // blk_k)
    else:
        n_iter = seq_len // blk_k
    dq = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(n_iter), body,
        jnp.zeros((blk_q, q.shape[-1]), jnp.float32))
    dq_ref[0] = (dq * jnp.float32(scale)).astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, mask_ref, dk_ref, dv_ref, *, scale,
                         causal, blk_q, blk_k, seq_len, dropout, has_mask):
    """grads wrt K and V: one (batch*head, k-block) program streaming Q
    blocks. dV = Pdrop^T dO; dK = dS^T Q * scale."""
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    seed = seed_ref[0, 0]
    k = k_ref[0]                                 # (blk_k, D)
    v = v_ref[0]
    mask_row = None
    if has_mask:
        mask_row = mask_ref[0, 0:1, pl.ds(ki * blk_k, blk_k)]

    def body(qj, carry):
        dk_acc, dv_acc = carry
        # causal: q-blocks before the diagonal contribute nothing; qb
        # indexes the tail [diag_start, nQ)
        if causal:
            qb = qj + ki * (blk_k // blk_q)
        else:
            qb = qj
        q = q_ref[0, pl.ds(qb * blk_q, blk_q), :]
        do = do_ref[0, pl.ds(qb * blk_q, blk_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * blk_q, blk_q)]
        delta = delta_ref[0, 0, pl.ds(qb * blk_q, blk_q)]
        ds, pd = _bwd_tile_ds(q, k, v, do, lse, delta, mask_row, causal,
                              dropout, scale, seed, bh, qb * blk_q,
                              ki * blk_k, blk_q, blk_k)
        dv_acc = dv_acc + jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    n_qb = seq_len // blk_q
    if causal:
        n_iter = n_qb - ki * (blk_k // blk_q)
    else:
        n_iter = n_qb
    D = k.shape[-1]
    dk, dv = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(n_iter), body,
        (jnp.zeros((blk_k, D), jnp.float32),
         jnp.zeros((blk_k, D), jnp.float32)))
    dk_ref[0] = (dk * jnp.float32(scale)).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ----------------------------------------------------------- pallas plumbing

def _prep(q, k, v, kv_mask, seed):
    B, H, S, D = q.shape
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)
    if kv_mask is None:
        mr = jnp.ones((B, 1, S), jnp.int32)  # dummy operand, loads elided
    else:
        mr = kv_mask.astype(jnp.int32).reshape(B, 1, S)
    if seed is None:
        sr = jnp.zeros((1, 1), jnp.int32)
    else:
        sr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    return qr, kr, vr, mr, sr


def _flash_fwd_impl(q, k, v, kv_mask, seed, causal, dropout, interpret):
    B, H, S, D = q.shape
    # plain Python float: np.float64 is strongly typed and would promote
    # the f32 kernel to f64 under x64 (TPU Mosaic has no 64-bit types)
    scale = float(1.0 / np.sqrt(D))
    blk_q, blk_k = _pick_blocks(S, causal)
    qr, kr, vr, mr, sr = _prep(q, k, v, kv_mask, seed)
    grid = (B * H, S // blk_q)
    kernel = functools.partial(
        _attn_fwd_kernel, scale=scale, causal=causal, blk_q=blk_q,
        blk_k=blk_k, seq_len=S, dropout=float(dropout),
        has_mask=kv_mask is not None)
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, 1, S), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i: (0, 0)),          # seed
            pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i, H=H: (b // H, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, 1, blk_q), lambda b, i: (b, 0, i))),
        interpret=interpret,
    )
    # trace with x64 off: this framework enables jax_enable_x64 globally
    # (int64 index parity), but Mosaic's grid machinery then emits i64
    # scalars that fail to legalize ('func.return') on the TPU compiler —
    # the kernel itself is pure f32/i32
    with jax.enable_x64(False):
        out, lse = call(sr, qr, kr, vr, mr)
    return out.reshape(B, H, S, D), lse


def _flash_bwd_impl(q, k, v, kv_mask, seed, o, lse, g, causal, dropout,
                    interpret):
    B, H, S, D = q.shape
    scale = float(1.0 / np.sqrt(D))
    blk_q, blk_k = _pick_blocks(S, causal)
    qr, kr, vr, mr, sr = _prep(q, k, v, kv_mask, seed)
    gr = g.reshape(B * H, S, D)
    orr = o.reshape(B * H, S, D)
    # delta_i = rowsum(dO o O): one fused XLA elementwise+reduce, O(S·D)
    delta = jnp.sum(gr.astype(jnp.float32) * orr.astype(jnp.float32),
                    axis=-1)[:, None, :]
    common = dict(scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                  seq_len=S, dropout=float(dropout),
                  has_mask=kv_mask is not None)
    seed_spec = pl.BlockSpec((1, 1), lambda b, i: (0, 0))
    mask_spec = pl.BlockSpec((1, 1, S), lambda b, i, H=H: (b // H, 0, 0))
    full_spec = pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0))
    row_full = pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0))

    dq_call = pl.pallas_call(
        functools.partial(_attn_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=(B * H, S // blk_q),
        in_specs=[
            seed_spec,
            pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),  # q
            full_spec,                                              # k
            full_spec,                                              # v
            pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),  # do
            pl.BlockSpec((1, 1, blk_q), lambda b, i: (b, 0, i)),  # lse
            pl.BlockSpec((1, 1, blk_q), lambda b, i: (b, 0, i)),  # delta
            mask_spec,
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )
    dkv_call = pl.pallas_call(
        functools.partial(_attn_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, S, D), v.dtype)),
        grid=(B * H, S // blk_k),
        in_specs=[
            seed_spec,
            full_spec,                                              # q
            pl.BlockSpec((1, blk_k, D), lambda b, i: (b, i, 0)),  # k
            pl.BlockSpec((1, blk_k, D), lambda b, i: (b, i, 0)),  # v
            full_spec,                                              # do
            row_full,                                               # lse
            row_full,                                               # delta
            mask_spec,
        ],
        out_specs=(pl.BlockSpec((1, blk_k, D), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, blk_k, D), lambda b, i: (b, i, 0))),
        interpret=interpret,
    )
    with jax.enable_x64(False):
        dq = dq_call(sr, qr, kr, vr, gr, lse, delta, mr)
        dk, dv = dkv_call(sr, qr, kr, vr, gr, lse, delta, mr)
    return (dq.reshape(B, H, S, D), dk.reshape(B, H, S, D),
            dv.reshape(B, H, S, D))


# ---------------------------------------------------------------- public API

def _reference_attention(q, k, v, causal, kv_mask=None):
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(D)
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :].astype(bool), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_attention(q, k, v, kv_mask=None, seed=None, causal=False,
                    dropout=0.0, interpret=False):
    """Blockwise exact attention, (B, H, S, D) layout.

    kv_mask: optional (B, S) key keep-mask (nonzero = attend).
    seed:    int32 scalar for attention dropout (required if dropout > 0).
    dropout: STATIC attention-probability dropout rate (traced under jit
             per distinct value; rates are fixed hyperparameters).
    """
    out, _ = _flash_fwd_impl(q, k, v, kv_mask, seed, causal, dropout,
                             interpret)
    return out


def _fa_fwd(q, k, v, kv_mask, seed, causal, dropout, interpret):
    out, lse = _flash_fwd_impl(q, k, v, kv_mask, seed, causal, dropout,
                               interpret)
    return out, (q, k, v, kv_mask, seed, out, lse)


def _fa_bwd(causal, dropout, interpret, res, g):
    q, k, v, kv_mask, seed, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, kv_mask, seed, o, lse, g,
                                 causal, dropout, interpret)
    return dq, dk, dv, None, None


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ===================================================================== BSHD
# Head-fused kernels operating directly on (B, S, H, D) tensors viewed as
# (B, S, H*D): the transformer's natural layout straight out of the qkv
# projection. Eliminates the (B,T,H,D)->(B,H,T,D) physical transposes the
# BHSD kernels force around every attention (XPlane: ~12% of a BERT-base
# s128 training span). Mosaic's tiling rule forbids per-head blocks
# ((..,1,D) over (..,H,D)), so each program loads full (blk, H*D) rows —
# every byte of which it needs — and statically unrolls the head loop.
# Requires H*D % 128 == 0.

def flash_attention_bshd_usable(q_shape, head_dim):
    if not _HAS_PALLAS:
        return False
    B, S, HD = q_shape[0], q_shape[1], int(np.prod(q_shape[2:]))
    # Each program holds two FULL (S, H*D) operands in VMEM (K+V in the
    # forward; Q+dO in the dkdv backward) plus block-sized tiles and fp32
    # accumulators. Bound that footprint well under the ~16 MB VMEM so
    # long-sequence/many-head shapes fall back to the per-head BHSD path
    # instead of failing Mosaic compilation.
    full_operand_bytes = 2 * S * HD * 4
    return (S % BLOCK_Q == 0 and S >= BLOCK_Q and HD % 128 == 0
            and head_dim <= 256
            and full_operand_bytes <= 8 * 1024 * 1024)


def _bshd_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref, o_ref,
                     lse_ref, *, scale, causal, blk_q, blk_k, seq_len,
                     dropout, has_mask, num_heads, head_dim):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    seed = seed_ref[0, 0]
    n_kb = seq_len // blk_k
    H, D = num_heads, head_dim

    for h in range(H):                            # static unroll
        q = q_ref[0, :, h * D:(h + 1) * D]
        bh = b * jnp.int32(H) + jnp.int32(h)

        def body(kb, carry, h=h, q=q, bh=bh):
            k = k_ref[0, pl.ds(kb * blk_k, blk_k), h * D:(h + 1) * D]
            v = v_ref[0, pl.ds(kb * blk_k, blk_k), h * D:(h + 1) * D]
            mrow = mask_ref[0, 0:1, pl.ds(kb * blk_k, blk_k)] \
                if has_mask else None
            dead = _tile_dead(causal, qi * blk_q, kb * blk_k, blk_q,
                              blk_k, mrow)
            _, carry = _fwd_tile_update(q, k, v, carry, dead, seed, bh,
                                        qi * blk_q, kb * blk_k, blk_q,
                                        blk_k, dropout, scale)
            return carry

        acc = jnp.zeros((blk_q, D), jnp.float32)
        m_i = jnp.full((blk_q,), jnp.float32(NEG_INF), jnp.float32)
        l_i = jnp.zeros((blk_q,), jnp.float32)
        if causal:
            n_iter = qi * (blk_q // blk_k) + (blk_q // blk_k)
        else:
            n_iter = n_kb
        acc, m_i, l_i = jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_iter),
                                          body, (acc, m_i, l_i))
        l_safe = jnp.maximum(l_i, jnp.float32(1e-20))
        o_ref[0, :, h * D:(h + 1) * D] = \
            (acc / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, :, h] = m_i + jnp.log(l_safe)


def _bshd_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, mask_ref, dq_ref, *, scale, causal,
                        blk_q, blk_k, seq_len, dropout, has_mask,
                        num_heads, head_dim):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    seed = seed_ref[0, 0]
    H, D = num_heads, head_dim

    for h in range(H):
        q = q_ref[0, :, h * D:(h + 1) * D]
        do = do_ref[0, :, h * D:(h + 1) * D]
        lse = lse_ref[0, 0, :, h]
        delta = delta_ref[0, 0, :, h]
        bh = b * jnp.int32(H) + jnp.int32(h)

        def body(kb, dq_acc, h=h, q=q, do=do, lse=lse, delta=delta, bh=bh):
            k = k_ref[0, pl.ds(kb * blk_k, blk_k), h * D:(h + 1) * D]
            v = v_ref[0, pl.ds(kb * blk_k, blk_k), h * D:(h + 1) * D]
            mask_row = None
            if has_mask:
                mask_row = mask_ref[0, 0:1, pl.ds(kb * blk_k, blk_k)]
            ds, _ = _bwd_tile_ds(q, k, v, do, lse, delta, mask_row,
                                 causal, dropout, scale, seed, bh,
                                 qi * blk_q, kb * blk_k, blk_q, blk_k)
            return dq_acc + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            n_iter = qi * (blk_q // blk_k) + (blk_q // blk_k)
        else:
            n_iter = seq_len // blk_k
        dq = jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_iter), body,
                               jnp.zeros((blk_q, D), jnp.float32))
        dq_ref[0, :, h * D:(h + 1) * D] = \
            (dq * jnp.float32(scale)).astype(dq_ref.dtype)


def _bshd_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, mask_ref, dk_ref, dv_ref, *, scale,
                         causal, blk_q, blk_k, seq_len, dropout, has_mask,
                         num_heads, head_dim):
    b = pl.program_id(0)
    ki = pl.program_id(1)
    seed = seed_ref[0, 0]
    H, D = num_heads, head_dim
    mask_row = None
    if has_mask:
        mask_row = mask_ref[0, 0:1, pl.ds(ki * blk_k, blk_k)]

    n_qb = seq_len // blk_q
    for h in range(H):
        k = k_ref[0, :, h * D:(h + 1) * D]
        v = v_ref[0, :, h * D:(h + 1) * D]
        bh = b * jnp.int32(H) + jnp.int32(h)

        def body(qj, carry, h=h, k=k, v=v, bh=bh):
            dk_acc, dv_acc = carry
            if causal:
                qb = qj + ki * (blk_k // blk_q)
            else:
                qb = qj
            q = q_ref[0, pl.ds(qb * blk_q, blk_q), h * D:(h + 1) * D]
            do = do_ref[0, pl.ds(qb * blk_q, blk_q), h * D:(h + 1) * D]
            lse = lse_ref[0, qb, :, h]
            delta = delta_ref[0, qb, :, h]
            ds, pd = _bwd_tile_ds(q, k, v, do, lse, delta, mask_row,
                                  causal, dropout, scale, seed, bh,
                                  qb * blk_q, ki * blk_k, blk_q, blk_k)
            dv_acc = dv_acc + jax.lax.dot_general(
                pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_acc = dk_acc + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk_acc, dv_acc

        if causal:
            n_iter = n_qb - ki * (blk_k // blk_q)
        else:
            n_iter = n_qb
        dk, dv = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(n_iter), body,
            (jnp.zeros((blk_k, D), jnp.float32),
             jnp.zeros((blk_k, D), jnp.float32)))
        dk_ref[0, :, h * D:(h + 1) * D] = \
            (dk * jnp.float32(scale)).astype(dk_ref.dtype)
        dv_ref[0, :, h * D:(h + 1) * D] = dv.astype(dv_ref.dtype)


def _bshd_prep(q, k, v, kv_mask, seed):
    B, S, H, D = q.shape
    qf = q.reshape(B, S, H * D)
    kf = k.reshape(B, S, H * D)
    vf = v.reshape(B, S, H * D)
    if kv_mask is None:
        mr = jnp.ones((B, 1, S), jnp.int32)
    else:
        mr = kv_mask.astype(jnp.int32).reshape(B, 1, S)
    if seed is None:
        sr = jnp.zeros((1, 1), jnp.int32)
    else:
        sr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    return qf, kf, vf, mr, sr


def _bshd_fwd_impl(q, k, v, kv_mask, seed, causal, dropout, interpret):
    B, S, H, D = q.shape
    HD = H * D
    scale = float(1.0 / np.sqrt(D))
    blk_q, blk_k = _pick_blocks_bshd(S, causal, HD, q.dtype.itemsize)
    qf, kf, vf, mr, sr = _bshd_prep(q, k, v, kv_mask, seed)
    n_q = S // blk_q
    kernel = functools.partial(
        _bshd_fwd_kernel, scale=scale, causal=causal, blk_q=blk_q,
        blk_k=blk_k, seq_len=S, dropout=float(dropout),
        has_mask=kv_mask is not None, num_heads=H, head_dim=D)
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((B, S, HD), q.dtype),
                   jax.ShapeDtypeStruct((B, n_q, blk_q, H),
                                        jnp.float32)),
        grid=(B, n_q),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i: (0, 0)),
            pl.BlockSpec((1, blk_q, HD), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, HD), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, HD), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, blk_q, HD), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, 1, blk_q, H),
                                lambda b, i: (b, i, 0, 0))),
        interpret=interpret,
    )
    with jax.enable_x64(False):
        out, lse = call(sr, qf, kf, vf, mr)
    return out.reshape(B, S, H, D), lse


def _bshd_bwd_impl(q, k, v, kv_mask, seed, o, lse, g, causal, dropout,
                   interpret):
    B, S, H, D = q.shape
    HD = H * D
    scale = float(1.0 / np.sqrt(D))
    blk_q, blk_k = _pick_blocks_bshd(S, causal, HD, q.dtype.itemsize)
    qf, kf, vf, mr, sr = _bshd_prep(q, k, v, kv_mask, seed)
    gf = g.reshape(B, S, HD)
    # delta = rowsum_d(dO o O) per head: (B, nQ, blk_q, H)
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                          # (B, S, H)
    n_q = S // blk_q
    delta = delta.reshape(B, n_q, blk_q, H)
    common = dict(scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                  seq_len=S, dropout=float(dropout),
                  has_mask=kv_mask is not None, num_heads=H, head_dim=D)
    seed_spec = pl.BlockSpec((1, 1), lambda b, i: (0, 0))
    mask_spec = pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0))
    full_spec = pl.BlockSpec((1, S, HD), lambda b, i: (b, 0, 0))
    blkq_spec = pl.BlockSpec((1, blk_q, HD), lambda b, i: (b, i, 0))
    blkk_spec = pl.BlockSpec((1, blk_k, HD), lambda b, i: (b, i, 0))
    lse_blk = pl.BlockSpec((1, 1, blk_q, H), lambda b, i: (b, i, 0, 0))
    lse_full = pl.BlockSpec((1, n_q, blk_q, H),
                            lambda b, i: (b, 0, 0, 0))

    dq_call = pl.pallas_call(
        functools.partial(_bshd_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((B, S, HD), q.dtype),
        grid=(B, n_q),
        in_specs=[seed_spec, blkq_spec, full_spec, full_spec, blkq_spec,
                  lse_blk, lse_blk, mask_spec],
        out_specs=blkq_spec,
        interpret=interpret,
    )
    dkv_call = pl.pallas_call(
        functools.partial(_bshd_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((B, S, HD), k.dtype),
                   jax.ShapeDtypeStruct((B, S, HD), v.dtype)),
        grid=(B, S // blk_k),
        in_specs=[seed_spec, full_spec, blkk_spec, blkk_spec, full_spec,
                  lse_full, lse_full, mask_spec],
        out_specs=(blkk_spec, blkk_spec),
        interpret=interpret,
    )
    with jax.enable_x64(False):
        dq = dq_call(sr, qf, kf, vf, gf, lse, delta, mr)
        dk, dv = dkv_call(sr, qf, kf, vf, gf, lse, delta, mr)
    return (dq.reshape(B, S, H, D), dk.reshape(B, S, H, D),
            dv.reshape(B, S, H, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_attention_bshd(q, k, v, kv_mask=None, seed=None, causal=False,
                         dropout=0.0, interpret=False):
    """Blockwise exact attention in (B, S, H, D) layout — no physical
    transpose between the qkv projection and the kernel. Same mask/
    dropout semantics as `flash_attention`."""
    out, _ = _bshd_fwd_impl(q, k, v, kv_mask, seed, causal, dropout,
                            interpret)
    return out


def _fab_fwd(q, k, v, kv_mask, seed, causal, dropout, interpret):
    out, lse = _bshd_fwd_impl(q, k, v, kv_mask, seed, causal, dropout,
                              interpret)
    return out, (q, k, v, kv_mask, seed, out, lse)


def _fab_bwd(causal, dropout, interpret, res, g):
    q, k, v, kv_mask, seed, o, lse = res
    dq, dk, dv = _bshd_bwd_impl(q, k, v, kv_mask, seed, o, lse, g,
                                causal, dropout, interpret)
    return dq, dk, dv, None, None


flash_attention_bshd.defvjp(_fab_fwd, _fab_bwd)
