"""Neural-network operators.

Role parity: reference ``src/operator/nn/`` (~29K LoC: convolution-inl.h,
fully_connected, pooling, batch_norm, layer_norm, softmax, dropout,
activation, rnn-inl.h RNNOp, + cudnn/ and mkldnn/ vendor forks).

TPU-native: every op lowers to XLA HLO via lax — conv_general_dilated hits
the MXU directly, reduce_window does pooling, and normalization/softmax are
fused elementwise chains XLA optimizes. No vendor forks: one code path for
eager and compiled, all layouts NCHW to match MXNet's API contract (XLA
re-layouts internally for the TPU).
"""
from __future__ import annotations

import os as _os

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

# round-5 perf-experiment gates (each a measured end-to-end loss in its
# default-off state -- see PERF.md round-5 study)
_POOL_EQBWD = _os.environ.get("MXTPU_MAXPOOL_EQBWD", "0") == "1"
_CONV_S2D = _os.environ.get("MXTPU_CONV_S2D", "0") == "1"
_BN_BARRIER = _os.environ.get("MXTPU_BN_BARRIER", "0") == "1"
# threefry restores jax.random.bernoulli dropout masks (10x costlier on
# the VPU than the default counter-hash; see PERF.md round-5 LM study)
_DROPOUT_THREEFRY = _os.environ.get("MXTPU_DROPOUT_THREEFRY", "0") == "1"

from ..base import dtype_np
from ._common import _bind_key, _bind_train
from .registry import register


# ------------------------------------------------------------ dense / conv


@register("FullyConnected", aliases=("fully_connected",))
def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):
    """reference `src/operator/nn/fully_connected.cc:258` registration,
    kernel `fully_connected-inl.h` (cuBLAS gemm) — here: one jnp.dot on the
    MXU, bf16-friendly."""
    if flatten and data.ndim > 2:
        data = data.reshape((data.shape[0], -1))
    out = jnp.dot(data, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


def _pair(v, n=2):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    return t if t else (1,) * n


def _conv_s2d_stride2(data, weight, padding):
    """Stride-2 conv with few input channels, rewritten via space-to-depth.

    A 7x7/s2 stem conv on 3 channels runs the MXU at ~3/128 packing — the
    round-5 profile measured the ResNet-50 stem fwd+dw at 5.2% of step time
    (~24 TFLOP/s vs the 54 conv ceiling). Mathematically identical rewrite:
    block-2 space-to-depth on the (padded) input (C -> 4C channels, half
    spatial) turns it into a ceil(k/2)^2 STRIDE-1 conv on 4C channels:
        out[o,i,j] = sum_{c,u,v} xp[c,2i+u,2j+v] w[o,c,u,v]
                   = sum_{c,r_u,r_v,q_u,q_v} X2[(c,ru,rv), i+qu, j+qv]
                                             W2[o,(c,ru,rv), qu, qv]
    with u = 2 qu + ru (kernel zero-padded k -> 2*ceil(k/2)). Same FLOPs,
    4x the MXU contraction depth, and the gradient convs (autodiff through
    the reshape/transpose) get the same packing win."""
    N, C, H, W = data.shape
    O, _, K, _ = weight.shape
    K2 = (K + 1) // 2
    xp = jnp.pad(data, [(0, 0), (0, 0), padding[0], padding[1]])
    Hp, Wp = xp.shape[2], xp.shape[3]
    x2 = xp.reshape(N, C, Hp // 2, 2, Wp // 2, 2)
    x2 = x2.transpose(0, 1, 3, 5, 2, 4).reshape(N, C * 4, Hp // 2, Wp // 2)
    wp = jnp.pad(weight, [(0, 0), (0, 0), (0, 2 * K2 - K), (0, 2 * K2 - K)])
    w2 = wp.reshape(O, C, K2, 2, K2, 2)
    w2 = w2.transpose(0, 1, 3, 5, 2, 4).reshape(O, C * 4, K2, K2)
    dn = lax.conv_dimension_numbers(x2.shape, w2.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(x2, w2, (1, 1), [(0, 0), (0, 0)],
                                    dimension_numbers=dn)


@register("Convolution", aliases=("convolution",))
def Convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                layout=None, cudnn_tune=None, cudnn_off=False, workspace=None):
    """reference `src/operator/nn/convolution-inl.h` — lowered to
    lax.conv_general_dilated (MXU systolic matmul path). Supports 1D/2D/3D
    NC* layouts + grouped conv."""
    nd = data.ndim - 2
    stride = _pair(stride, nd)
    dilate = _pair(dilate, nd)
    pad = _pair(pad, nd) if pad else (0,) * nd
    padding = [(p, p) for p in pad]
    if (_CONV_S2D and nd == 2 and num_group == 1 and stride == (2, 2)
            and dilate == (1, 1)
            and weight.ndim == 4 and weight.shape[1] * weight.shape[2] <= 32
            and weight.shape[2] == weight.shape[3]
            and weight.shape[2] % 2 == 1 and weight.shape[2] >= 5
            and (data.shape[2] + 2 * pad[0]) % 2 == 0
            and (data.shape[3] + 2 * pad[1]) % 2 == 0):
        # OFF by default: measured on-chip (round 5, ResNet-50 b32) the
        # space-to-depth shuffle cost exceeded the MXU-packing gain
        # (2695 vs 2782 img/s end-to-end, barrier'd or fused) — the stem
        # conv is latency- not depth-bound at these shapes. Kept behind
        # MXTPU_CONV_S2D=1; the rewrite itself is oracle-exact.
        out = _conv_s2d_stride2(data, weight, padding)
        if not no_bias and bias is not None:
            out = out + bias.reshape((1, -1, 1, 1))
        return out
    dn_str = {1: ("NCH", "OIH", "NCH"),
              2: ("NCHW", "OIHW", "NCHW"),
              3: ("NCDHW", "OIDHW", "NCDHW")}[nd]
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, dn_str)
    # NB: no preferred_element_type override — XLA already accumulates bf16
    # convs in fp32 on the TPU MXU, and an explicit f32 override breaks the
    # transpose (VJP) rule's dtype matching.
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", aliases=("deconvolution",))
def Deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), num_filter=0, num_group=1, no_bias=True,
                  layout=None, target_shape=None, cudnn_tune=None,
                  cudnn_off=False, workspace=None):
    """reference `src/operator/nn/deconvolution-inl.h` — transposed conv via
    lax.conv_transpose."""
    nd = data.ndim - 2
    stride = _pair(stride, nd)
    dilate = _pair(dilate, nd)
    pad = _pair(pad, nd) if pad else (0,) * nd
    kernel = _pair(kernel, nd)
    adj = _pair(adj, nd) if adj else (0,) * nd
    # output padding semantics: out = (in-1)*s - 2p + dil*(k-1) + 1 + adj
    padding = []
    for p, k, d, a in zip(pad, kernel, dilate, adj):
        eff_k = d * (k - 1) + 1
        padding.append((eff_k - 1 - p, eff_k - 1 - p + a))
    # MXNet deconv weight layout is (C_in, C_out/g, k...): the transposed
    # conv is a regular conv with spatially-mirrored kernel and I/O swapped
    # (what lax's removed transpose_kernel flag used to do).
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    dn_str = {1: ("NCH", "IOH", "NCH"),
              2: ("NCHW", "IOHW", "NCHW"),
              3: ("NCDHW", "IODHW", "NCDHW")}[nd]
    dn = lax.conv_dimension_numbers(data.shape, w.shape, dn_str)
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out




@jax.custom_vjp
def _fwd_barrier(x):
    """optimization_barrier in the forward pass only; gradients flow
    through untouched (a plain barrier transposes to a cotangent barrier,
    which breaks backward fusions)."""
    return lax.optimization_barrier(x)


_fwd_barrier.defvjp(lambda x: (lax.optimization_barrier(x), None),
                    lambda _, g: (g,))


# -- max-pool with a TPU-friendly backward ---------------------------------
#
# XLA derives reduce_window's max-pool gradient as select-and-scatter, which
# the round-2/round-5 profiles measured as the single slowest HLO in the
# ResNet-50 step (3.8% of device time for ONE op, plus a 1.8% forward that
# re-reads windows). This custom VJP keeps the reduce_window forward but
# replaces the backward with an equality-spread: each input position checks
# the <=ceil(k/s)^2 windows that cover it and accumulates g/count for every
# window whose max it equals (count = number of tied positions, computed
# with k^2 strided slices in output space). Tie handling differs from
# select-and-scatter (which gives the whole gradient to the FIRST max):
# ties SHARE the gradient — per-window gradient mass is identical, and for
# the no-tie case (distinct window values) the two are exactly equal.

def _cover_indices(in_size, out_size, k, s, p):
    """Per input coordinate y, the <=2 output windows covering it (valid
    for k <= 2s): index vectors (lo, hi) and hi's validity mask."""
    yp = _np.arange(in_size) + p
    lo = (yp - k + s) // s          # ceil((yp - k + 1) / s)
    hi = yp // s
    # full membership check (window i covers yp iff i*s <= yp < i*s + k):
    # with k < s there are inter-window gaps, and a clamped/gap index must
    # not claim coverage
    lo_ok = (lo >= 0) & (lo <= out_size - 1) & \
        (lo * s <= yp) & (lo * s + k > yp)
    hi_ok = (hi >= 0) & (hi <= out_size - 1) & (hi != lo) & \
        (hi * s <= yp) & (hi * s + k > yp)
    return (_np.clip(lo, 0, out_size - 1), lo_ok,
            _np.clip(hi, 0, out_size - 1), hi_ok)


def _maxpool2d_fwd(data, kernel, stride, padding):
    init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
        else jnp.asarray(jnp.iinfo(data.dtype).min, data.dtype)
    return lax.reduce_window(data, init, lax.max, (1, 1) + kernel,
                             (1, 1) + stride, [(0, 0), (0, 0)] + padding)


def _maxpool2d_nchw_bwd(kernel, stride, padding, res, g):
    data, out = res
    (kh, kw), (sh, sw) = kernel, stride
    (ph, _), (pw, _) = padding
    N, C, H, W = data.shape
    OH, OW = out.shape[2], out.shape[3]
    neg = jnp.asarray(-jnp.inf, data.dtype)
    xp = jnp.pad(data, [(0, 0), (0, 0), padding[0], padding[1]],
                 constant_values=neg)
    # ties per window: k*k strided slices of the padded input, all fused
    # into one elementwise pass in output space
    count = None
    for dy in range(kh):
        for dx in range(kw):
            sl = lax.slice(xp, (0, 0, dy, dx),
                           (N, C, dy + sh * (OH - 1) + 1,
                            dx + sw * (OW - 1) + 1), (1, 1, sh, sw))
            eq = (sl == out).astype(jnp.float32)
            count = eq if count is None else count + eq
    gn = (g.astype(jnp.float32) / count).astype(data.dtype)
    # spread back: for each of the <=2x2 covering windows per position,
    # gather out/gn rows (constant index vectors -> fused gathers) and
    # accumulate where the input equals the window max
    ylo, ylo_ok, yhi, yhi_ok = _cover_indices(H, OH, kh, sh, ph)
    xlo, xlo_ok, xhi, xhi_ok = _cover_indices(W, OW, kw, sw, pw)
    gin = jnp.zeros(data.shape, data.dtype)
    for yi, ym in ((ylo, ylo_ok), (yhi, yhi_ok)):
        for xi, xm in ((xlo, xlo_ok), (xhi, xhi_ok)):
            o = jnp.take(jnp.take(out, yi, axis=2), xi, axis=3)
            gv = jnp.take(jnp.take(gn, yi, axis=2), xi, axis=3)
            m = (ym[:, None] & xm[None, :])
            gin = gin + jnp.where((data == o) & m, gv,
                                  jnp.zeros((), data.dtype))
    return (gin,)


# kernel/stride/padding are static python values (nondiff)
_maxpool2d_nchw = jax.custom_vjp(_maxpool2d_fwd, nondiff_argnums=(1, 2, 3))


def _maxpool2d_res_fwd(data, kernel, stride, padding):
    out = _maxpool2d_fwd(data, kernel, stride, padding)
    return out, (data, out)


_maxpool2d_nchw.defvjp(_maxpool2d_res_fwd, _maxpool2d_nchw_bwd)


@register("Pooling", aliases=("pooling",))
def Pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
            global_pool=False, pooling_convention="valid", cudnn_off=False,
            p_value=2, count_include_pad=True, layout=None):
    """reference `src/operator/nn/pooling-inl.h` — lax.reduce_window."""
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=axes, keepdims=True)
        if pool_type == "lp":
            return jnp.power(jnp.sum(jnp.power(jnp.abs(data), p_value),
                                     axis=axes, keepdims=True), 1.0 / p_value)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _pair(kernel, nd)
    stride = _pair(stride, nd)
    pad = _pair(pad, nd) if pad else (0,) * nd
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad high edge enough for a final partial window
        padding = [(0, 0), (0, 0)]
        for i in range(nd):
            size = data.shape[2 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra = (stride[i] - rem) % stride[i] if rem else 0
            padding.append((pad[i], pad[i] + extra))
    else:
        padding = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    if pool_type == "max":
        spad = padding[2:]
        if (_POOL_EQBWD and nd == 2
                and jnp.issubdtype(data.dtype, jnp.floating)
                and all(k <= 2 * s for k, s in zip(kernel, stride))
                and all(p[0] == p[1] for p in spad)):
            # Equality-spread backward (see _maxpool2d_nchw above). OFF by
            # default: measured on-chip (round 5), the gather-based spread
            # lowered to materialized layout copies and LOST ~25% end-to-end
            # vs XLA's select-and-scatter; kept behind MXTPU_MAXPOOL_EQBWD=1
            # for future reruns against newer XLA gather fusion.
            return _maxpool2d_nchw(data, kernel, stride, list(spad))
        init = (-jnp.inf if jnp.issubdtype(data.dtype, jnp.floating)
                else jnp.asarray(jnp.iinfo(data.dtype).min, data.dtype))
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum", "lp"):
        x = jnp.power(jnp.abs(data), p_value) if pool_type == "lp" else data
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        if pool_type == "lp":
            return jnp.power(s, 1.0 / p_value)
        if count_include_pad:
            return s / _np.prod(kernel)
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return s / cnt
    raise ValueError("unknown pool_type %s" % pool_type)


# AdaptiveAvgPooling2D / BilinearResize2D live in detection_ops.py
# (exact integral-image windows + mode='like' support).


# ------------------------------------------------------------ activations


@register("Activation", aliases=("activation",))
def Activation(data, act_type="relu"):
    """reference `src/operator/nn/activation-inl.h`."""
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %s" % act_type)


@register("relu")
def relu(data):
    return jax.nn.relu(data)


@register("sigmoid")
def sigmoid(data):
    return jax.nn.sigmoid(data)


@register("hard_sigmoid")
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("softsign")
def softsign(data):
    return jax.nn.soft_sign(data)


@register("softrelu")
def softrelu(data):
    return jax.nn.softplus(data)


@register("gelu", aliases=("LeakyReLU_gelu", "_contrib_gelu"))
def gelu(data):
    return jax.nn.gelu(data, approximate=False)


@register("LeakyReLU",
          state_binders={"key": _bind_key, "train": _bind_train})
def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25,
              lower_bound=0.125, upper_bound=0.334, key=None, train=False):
    """reference `src/operator/leaky_relu-inl.h` — leaky/prelu/elu/selu/gelu/
    rrelu variants."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim == 1 and data.ndim > 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        if train:
            u = jax.random.uniform(key, data.shape, data.dtype,
                                   lower_bound, upper_bound)
            return jnp.where(data > 0, data, u * data)
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, mid * data)
    raise ValueError("unknown act_type %s" % act_type)


# ------------------------------------------------------------ softmax family


@register("softmax")
def softmax(data, axis=-1, length=None, temperature=None, dtype=None,
            use_length=False):
    """reference `src/operator/nn/softmax-inl.h`."""
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if use_length and length is not None:
        steps = jnp.arange(x.shape[axis])
        shp = [1] * x.ndim
        shp[axis] = x.shape[axis]
        mask = steps.reshape(shp) < jnp.expand_dims(length, axis=axis)
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    out = jax.nn.softmax(x, axis=axis)
    return out.astype(dtype_np(dtype)) if dtype else out


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None, dtype=None, use_length=False,
                length=None):
    x = data if temperature in (None, 1.0) else data / temperature
    out = jax.nn.log_softmax(x, axis=axis)
    return out.astype(dtype_np(dtype)) if dtype else out


@register("softmin")
def softmin(data, axis=-1, temperature=None, dtype=None):
    return softmax.fn(-data, axis=axis, temperature=temperature, dtype=dtype)


@register("SoftmaxActivation")
def SoftmaxActivation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register("SoftmaxOutput", aliases=("softmax_output", "Softmax"))
def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1.0,
                  multi_output=False, use_ignore=False, preserve_shape=False,
                  normalization="null", out_grad=False, smooth_alpha=0.0):
    """reference `src/operator/softmax_output-inl.h` — forward is softmax;
    the custom gradient (softmax-minus-onehot) is wired via custom_vjp so
    `backward` reproduces MXNet's loss-layer semantics."""
    return _softmax_output(data, label, grad_scale, ignore_label,
                           float(use_ignore), float(multi_output))


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _softmax_output(data, label, grad_scale, ignore_label, use_ignore,
                    multi_output):
    axis = 1 if multi_output else -1
    return jax.nn.softmax(data, axis=axis)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output):
    axis = 1 if multi_output else -1
    out = jax.nn.softmax(data, axis=axis)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, multi_output,
                        res, g):
    out, label = res
    axis = 1 if multi_output else -1
    depth = out.shape[axis]
    oh = jax.nn.one_hot(label.astype(jnp.int32), depth, axis=axis,
                        dtype=out.dtype)
    grad = (out - oh) * grad_scale
    if use_ignore:
        keep = (label != ignore_label).astype(out.dtype)
        keep = jnp.expand_dims(keep, axis=axis)
        grad = grad * keep
    # match batch mean semantics of MXNet: grad already per-example
    return (grad, jnp.zeros_like(label, dtype=out.dtype))


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    # label < 0 = ignore (native RecordIO emits -1 for corrupt records)
    logp = jax.nn.log_softmax(data, axis=-1)
    idx = label.astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, jnp.maximum(idx, 0)[:, None], axis=-1)
    nll = jnp.where(idx[:, None] >= 0, nll, 0.0)
    return jnp.sum(nll)


# ------------------------------------------------------------ normalization


@register("BatchNorm", aliases=("batch_norm", "BatchNorm_v1"),
          state_binders={"train": _bind_train})
def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=False,
              min_calib_range=None, max_calib_range=None, train=False):
    """reference `src/operator/nn/batch_norm-inl.h`. Note: running-stat
    *updates* are handled functionally by the Gluon layer (gluon/nn/basic_layers
    BatchNorm) — this op is the pure compute. The train flag is bound at
    invoke time so backward replay keeps batch-stat mode."""
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if use_global_stats or not train:
        mean, var = moving_mean, moving_var
    else:
        # one-pass stats (E[x^2] - E[x]^2, accumulated in fp32): both
        # reductions fuse into a single sweep over the activations, unlike
        # jnp.var which re-reads data after computing the mean. Same
        # formulation and precision as cuDNN/TF fused batch norm (the
        # reference's backend); fp32 accumulation bounds the cancellation
        # error at ~mean^2 * 2^-24, which the max(.., 0) clamp backstops.
        if _BN_BARRIER:
            # Keep the stat reductions OUT of the producing conv's fusion:
            # measured on-chip (round 5, scan probes at ResNet stage-2/3
            # shapes), a conv with BN-stat epilogue fused runs at 74-80
            # TFLOP/s vs 86-96 with this barrier (+17-20%). Forward-only
            # (identity gradient): a plain optimization_barrier transposes
            # to a cotangent barrier that measurably breaks backward
            # fusions (2495 vs 2772 img/s end-to-end ResNet-50).
            data = _fwd_barrier(data)
        xf = data.astype(jnp.float32)
        mean = jnp.mean(xf, axis=reduce_axes)
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), axis=reduce_axes) - jnp.square(mean),
            0.0)
        mean = mean.astype(moving_mean.dtype)
        var = var.astype(moving_var.dtype)
    inv = lax.rsqrt(var + eps).astype(data.dtype)
    out = (data - mean.reshape(bshape).astype(data.dtype)) * inv.reshape(bshape) \
        * g.reshape(bshape).astype(data.dtype) + beta.reshape(bshape).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("LayerNorm", aliases=("layer_norm",))
def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """reference `src/operator/nn/layer_norm-inl.h`."""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    ax = axis if axis >= 0 else data.ndim + axis
    bshape[ax] = data.shape[ax]
    out = (data - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    return out


@register("InstanceNorm")
def InstanceNorm(data, gamma, beta, eps=1e-3):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) \
        + beta.reshape(bshape)


@register("GroupNorm")
def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5):
    b, c = data.shape[:2]
    rest = data.shape[2:]
    x = data.reshape((b, num_groups, c // num_groups) + rest)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def L2Normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / nrm


@register("LRN")
def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(data)
    for i in range(nsize):
        acc = acc + padded[:, i:i + data.shape[1]]
    return data / jnp.power(knorm + alpha / nsize * acc, beta)


# ------------------------------------------------------------ dropout & rng


def _hash_keep_mask(key, shape, keep_prob):
    """Counter-hash keep mask: lowbias32 over the element's linear index
    mixed with the key — the same PRNG the Pallas flash kernel uses for
    in-kernel dropout (`pallas_kernels._keep_bits`). Deterministic in
    (key, shape), platform-independent, and ~10x cheaper on the VPU than
    threefry: the round-5 XPlane study measured threefry mask generation
    at 21% of a BERT-base s128 training step (5 loop fusions of ~3 ms/step
    emitting pred[64,128,768] masks)."""
    kd = key
    if jnp.issubdtype(kd.dtype, jax.dtypes.prng_key):
        kd = jax.random.key_data(kd)
    kd = kd.reshape(-1).astype(jnp.uint32)
    s0, s1 = kd[0], kd[-1]
    U = jnp.uint32
    idx = jnp.zeros(shape, U)
    stride = 1
    for ax in range(len(shape) - 1, -1, -1):
        idx = idx + lax.broadcasted_iota(U, tuple(shape), ax) * U(stride)
        stride *= shape[ax]
    from .pallas_kernels import _lowbias32
    c = idx * U(0x9E3779B9) ^ s0 * U(0x85EBCA6B) ^ s1 * U(0xC2B2AE35)
    c = _lowbias32(c)
    thresh = U(min(int(keep_prob * 4294967296.0), 4294967295))
    return c < thresh


@register("Dropout", aliases=("dropout",),
          state_binders={"key": _bind_key, "train": _bind_train})
def Dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False,
            key=None, train=False):
    """reference `src/operator/nn/dropout-inl.h`. The RNG key and train flag
    are bound at invoke time (state_binders) so tape replay is deterministic;
    under jit the key is a tracer split from the per-call base key."""
    if (not train and mode != "always") or p <= 0.0:
        return data
    shape = list(data.shape)
    for ax in (axes or ()):
        shape[ax] = 1
    if _DROPOUT_THREEFRY:
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    else:
        keep = _hash_keep_mask(key, tuple(shape), 1.0 - p)
    return jnp.where(keep, data / (1.0 - p), jnp.zeros((), dtype=data.dtype))


# samplers as ops (MXNet `_random_*` / `_sample_*` namespaces,
# reference src/operator/random/sample_op.cc)
@register("_random_uniform", differentiable=False)
def _random_uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None):
    from .. import random as _rnd
    return jax.random.uniform(_rnd.next_key(), tuple(shape),
                              dtype_np(dtype), low, high)


@register("_random_normal", differentiable=False)
def _random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None):
    from .. import random as _rnd
    return loc + scale * jax.random.normal(_rnd.next_key(), tuple(shape),
                                           dtype_np(dtype))


@register("_sample_uniform", differentiable=False)
def _sample_uniform(low, high, shape=(), dtype="float32"):
    from .. import random as _rnd
    s = tuple(low.shape) + tuple(shape)
    u = jax.random.uniform(_rnd.next_key(), s, dtype_np(dtype))
    bshape = low.shape + (1,) * len(tuple(shape))
    return low.reshape(bshape) + u * (high - low).reshape(bshape)


@register("_sample_normal", differentiable=False)
def _sample_normal(mu, sigma, shape=(), dtype="float32"):
    from .. import random as _rnd
    s = tuple(mu.shape) + tuple(shape)
    n = jax.random.normal(_rnd.next_key(), s, dtype_np(dtype))
    bshape = mu.shape + (1,) * len(tuple(shape))
    return mu.reshape(bshape) + n * sigma.reshape(bshape)


# ------------------------------------------------------------ embedding-ish


@register("batch_take")
def batch_take(a, indices):
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32).reshape(-1, 1), axis=1).reshape(-1)


@register("UpSampling")
def UpSampling(*data, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=None):
    x = data[0]
    b, c, h, w = x.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    else:
        out = jax.image.resize(x, (b, c, h * scale, w * scale), "linear")
    return out


# ------------------------------------------------------------ attention


def _flash_enabled():
    """Single gate for the pallas flash-attention dispatch: the
    registered ``MXNET_FLASH_ATTENTION`` knob (0 disables — the
    with/without benchmark switch) plus the legacy ``MXTPU_DISABLE_FLASH``
    escape hatch."""
    import os
    if os.environ.get("MXTPU_DISABLE_FLASH"):
        return False
    from .. import config as _config
    return bool(_config.get("MXNET_FLASH_ATTENTION"))


def _reduce_key_mask(mask, batch, key_len):
    """Reduce a BERT-style broadcastable keep-mask to (B, S_k) for the
    flash kernels. Returns (kv_mask, ok): ok=False means the mask shape
    is unsupported by the fused path (full (B,H,Q,K) masks etc.)."""
    if mask is None:
        return None, True
    nd = getattr(mask, "ndim", 0)
    if nd == 4 and mask.shape[1] == 1 and mask.shape[2] == 1 and \
            mask.shape[0] == batch and mask.shape[3] == key_len:
        return mask[:, 0, 0, :], True
    if nd == 2 and mask.shape == (batch, key_len):
        return mask, True
    return None, False


@register("_contrib_dot_product_attention",
          state_binders={"rng_key": _bind_key, "train": _bind_train})
def dot_product_attention(query, key, value, mask=None, dropout=0.0,
                          scaled=True, causal=False, layout="BHSD",
                          rng_key=None, train=False):
    """TPU-native fused attention entry. Not in MXNet 1.6 (attention was
    composed from ops there) — exposed as a contrib op. When the problem
    aligns to the pallas tiling (seq % 128 == 0) and a TPU is present,
    lowers to the flash-attention pallas kernel (ops/pallas_kernels.py) —
    including BERT's padding keep-mask ((B,1,1,T) or (B,T), reduced to a
    per-key mask) and train-time attention dropout (in-kernel counter RNG,
    fwd/bwd consistent). Full (B,H,Q,K) masks and cross-attention take the
    XLA softmax path below."""
    import os
    if layout == "BSHD" and getattr(query, "ndim", 0) == 4:
        # (B, S, H, D) — the transformer's natural layout straight out of
        # the qkv projection. The head-fused kernel consumes it with NO
        # physical transpose (the BHSD kernels force one on each side:
        # ~12% of a BERT-base s128 span per the XPlane study in PERF.md).
        from .pallas_kernels import (flash_attention_bshd,
                                     flash_attention_bshd_usable)
        kv_mask, mask_ok = _reduce_key_mask(mask, query.shape[0],
                                            key.shape[1])
        drop = float(dropout) if train else 0.0
        if (scaled and mask_ok and key.shape == query.shape
                and value.shape == query.shape
                and (drop == 0.0 or rng_key is not None)
                and flash_attention_bshd_usable(query.shape,
                                                query.shape[-1])
                and _flash_enabled()):
            try:
                on_tpu = any(d.platform not in ("cpu",)
                             for d in jax.devices())
            except RuntimeError:
                on_tpu = False
            if on_tpu:
                seed = None
                if drop > 0.0:
                    seed = jax.random.randint(
                        rng_key, (), -2**31, 2**31 - 1, dtype=jnp.int32)
                return flash_attention_bshd(query, key, value, kv_mask,
                                            seed, causal, drop)
        # fallback: run the BHSD path and restore the layout; XLA fuses
        # these transposes into the surrounding einsums. (.fn: the module
        # name is the registered Op wrapper, whose __call__ re-wraps)
        out = dot_product_attention.fn(
            jnp.transpose(query, (0, 2, 1, 3)),
            jnp.transpose(key, (0, 2, 1, 3)),
            jnp.transpose(value, (0, 2, 1, 3)),
            mask=mask, dropout=dropout, scaled=scaled, causal=causal,
            layout="BHSD", rng_key=rng_key, train=train)
        return jnp.transpose(out, (0, 2, 1, 3))

    if query.ndim == 4 and scaled and _flash_enabled():
        from .pallas_kernels import flash_attention, flash_attention_usable
        # BERT-style key padding masks broadcast over q: reducible to (B,S)
        kv_mask, mask_ok = _reduce_key_mask(mask, query.shape[0],
                                            key.shape[2])
        drop = float(dropout) if train else 0.0
        # kernel tiles assume self-attention layout; cross-attention with
        # kv_len != q_len must take the XLA path
        if (mask_ok and key.shape == query.shape
                and value.shape == query.shape
                and (drop == 0.0 or rng_key is not None)
                and flash_attention_usable(query.shape, causal)):
            try:
                on_tpu = any(d.platform not in ("cpu",)
                             for d in jax.devices())
            except RuntimeError:
                on_tpu = False
            if on_tpu:
                seed = None
                if drop > 0.0:
                    seed = jax.random.randint(
                        rng_key, (), -2**31, 2**31 - 1, dtype=jnp.int32)
                return flash_attention(query, key, value, kv_mask, seed,
                                       causal, drop)
    d = query.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", query, key)
    if scaled:
        scores = scores / _np.sqrt(d).astype(scores.dtype)
    if causal:
        q, k = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((q, k), dtype=bool))
        scores = jnp.where(cm, scores, jnp.finfo(scores.dtype).min)
    if mask is not None:
        m = mask
        if getattr(m, "ndim", 0) == 2 and scores.ndim == 4 and \
                m.shape == (scores.shape[0], scores.shape[-1]):
            m = m[:, None, None, :]  # (B,T) key mask -> broadcast form
        scores = jnp.where(m.astype(bool), scores, jnp.finfo(scores.dtype).min)
    w = jax.nn.softmax(scores, axis=-1)
    if dropout > 0.0 and train:
        keep = jax.random.bernoulli(rng_key, 1.0 - dropout, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout), 0.0)
    return jnp.einsum("...qk,...kd->...qd", w, value)
