"""Tensor-op breadth: scalar/logical variants, creation, indexing/assign,
misc shape ops.

Role parity: the remaining registrations of reference
``src/operator/tensor/`` (elemwise_binary_scalar_op_*.cc, init_op.cc,
matrix_op.cc slice-assign family, ravel.cc, histogram.cc, shuffle_op.cc,
square_sum.cc, elemwise_sum.cc) — each a one-liner onto jax.numpy/lax with
XLA supplying kernels and fusion.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import dtype_np
from ._common import _bind_key, _RNG, _dt  # noqa: F401
from .registry import register, register_alias, get_op

# ----------------------------------------------------- scalar comparisons
# (reference elemwise_binary_scalar_op_logic.cc — result keeps input dtype)


@register("_equal_scalar", aliases=("_EqualScalar",))
def _equal_scalar(data, scalar=0.0):
    return (data == scalar).astype(data.dtype)


@register("_not_equal_scalar", aliases=("_NotEqualScalar",))
def _not_equal_scalar(data, scalar=0.0):
    return (data != scalar).astype(data.dtype)


@register("_greater_scalar", aliases=("_GreaterScalar",))
def _greater_scalar(data, scalar=0.0):
    return (data > scalar).astype(data.dtype)


@register("_greater_equal_scalar", aliases=("_GreaterEqualScalar",))
def _greater_equal_scalar(data, scalar=0.0):
    return (data >= scalar).astype(data.dtype)


@register("_lesser_scalar", aliases=("_LesserScalar",))
def _lesser_scalar(data, scalar=0.0):
    return (data < scalar).astype(data.dtype)


@register("_lesser_equal_scalar", aliases=("_LesserEqualScalar",))
def _lesser_equal_scalar(data, scalar=0.0):
    return (data <= scalar).astype(data.dtype)


@register("_maximum_scalar", aliases=("_MaximumScalar",))
def _maximum_scalar(data, scalar=0.0):
    return jnp.maximum(data, scalar)


@register("_minimum_scalar", aliases=("_MinimumScalar",))
def _minimum_scalar(data, scalar=0.0):
    return jnp.minimum(data, scalar)


@register("_mod_scalar", aliases=("_ModScalar",))
def _mod_scalar(data, scalar=1.0):
    return jnp.mod(data, jnp.asarray(scalar, data.dtype))


@register("_rmod_scalar", aliases=("_RModScalar",))
def _rmod_scalar(data, scalar=1.0):
    return jnp.mod(jnp.asarray(scalar, data.dtype), data)


@register("_hypot_scalar", aliases=("_HypotScalar",))
def _hypot_scalar(data, scalar=0.0):
    return jnp.hypot(data, jnp.asarray(scalar, data.dtype))


@register("_logical_and_scalar", aliases=("_LogicalAndScalar",))
def _logical_and_scalar(data, scalar=1.0):
    return jnp.logical_and(data, scalar).astype(data.dtype)


@register("_logical_or_scalar", aliases=("_LogicalOrScalar",))
def _logical_or_scalar(data, scalar=1.0):
    return jnp.logical_or(data, scalar).astype(data.dtype)


@register("_logical_xor_scalar", aliases=("_LogicalXorScalar",))
def _logical_xor_scalar(data, scalar=1.0):
    return jnp.logical_xor(data, scalar).astype(data.dtype)


@register("_logical_and", aliases=("_Logical_And",))
def _logical_and(lhs, rhs):
    return jnp.logical_and(lhs, rhs).astype(lhs.dtype)


@register("_logical_or", aliases=("_Logical_Or",))
def _logical_or(lhs, rhs):
    return jnp.logical_or(lhs, rhs).astype(lhs.dtype)


@register("_logical_xor", aliases=("_Logical_Xor",))
def _logical_xor(lhs, rhs):
    return jnp.logical_xor(lhs, rhs).astype(lhs.dtype)


# CamelCase legacy registrations of existing scalar/binary ops
# (reference registers both spellings, e.g. _PlusScalar/_plus_scalar)
register_alias("_plus_scalar", "_PlusScalar")
register_alias("_minus_scalar", "_MinusScalar")
register_alias("_rminus_scalar", "_RMinusScalar")
register_alias("_mul_scalar", "_MulScalar")
register_alias("_div_scalar", "_DivScalar")
register_alias("_rdiv_scalar", "_RDivScalar")
register_alias("_power_scalar", "_PowerScalar")
register_alias("_rpower_scalar", "_RPowerScalar")
register_alias("hypot", "_hypot", "_Hypot")
register_alias("mod", "_mod", "_Mod")
register_alias("lesser", "less")
register_alias("lesser_equal", "less_equal")
register_alias("add", "_grad_add")
register_alias("pick", "choose_element_0index")

# ------------------------------------------------------------- creation
# (reference src/operator/tensor/init_op.cc)




@register("_arange", aliases=("_contrib_arange",))
def _arange(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False,
            ctx=None, dtype=None):
    out = jnp.arange(start, stop, step, dtype=_dt(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace")
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, ctx=None,
              dtype=None):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=_dt(dtype))


@register("_eye")
def _eye(N=0, M=0, k=0, ctx=None, dtype=None):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=_dt(dtype))


@register("_full")
def _full(shape=None, value=0.0, ctx=None, dtype=None):
    return jnp.full(tuple(shape), value, dtype=_dt(dtype))


@register("_ones")
def _ones(shape=None, ctx=None, dtype=None):
    return jnp.ones(tuple(shape), dtype=_dt(dtype))


@register("_zeros", aliases=("_zeros_without_dtype",))
def _zeros(shape=None, ctx=None, dtype=None):
    return jnp.zeros(tuple(shape), dtype=_dt(dtype))


@register("_histogram", n_out=2, differentiable=False)
def _histogram(data, bins=10, range=None, bin_cnt=None):
    if hasattr(bins, "shape") and getattr(bins, "ndim", 0) >= 1:
        hist, edges = jnp.histogram(data, bins=bins)
    else:
        hist, edges = jnp.histogram(
            data, bins=int(bin_cnt or bins),
            range=tuple(range) if range is not None else None)
    return hist, edges




@register("_shuffle", aliases=("shuffle",), differentiable=False,
          state_binders={"key": _bind_key})
def _shuffle(data, key=None):
    """Random first-axis permutation (reference shuffle_op.cc)."""
    return jax.random.permutation(key, data, axis=0)


# ------------------------------------------------- indexing / assignment
# (reference matrix_op.cc slice-assign family, ravel.cc)


@register("_ravel_multi_index", aliases=("ravel_multi_index",))
def _ravel_multi_index(data, shape=None):
    """data: (ndim, N) multi-indices -> (N,) flat indices."""
    idx = tuple(data[i].astype(jnp.int64) for i in range(len(shape)))
    return jnp.ravel_multi_index(idx, tuple(int(s) for s in shape),
                                 mode="clip").astype(data.dtype)


@register("_unravel_index", aliases=("unravel_index",))
def _unravel_index(data, shape=None):
    """data: (N,) flat indices -> (ndim, N) multi-indices."""
    parts = jnp.unravel_index(data.astype(jnp.int64),
                              tuple(int(s) for s in shape))
    return jnp.stack([p.astype(data.dtype) for p in parts], axis=0)


def _slice_tuple(shape, begin, end, step=None):
    ndim = len(shape)
    step = step or [None] * ndim
    sl = []
    for i in range(ndim):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        s = step[i] if i < len(step) else None
        sl.append(slice(b, e, s))
    return tuple(sl)


@register("_slice_assign", aliases=("_crop_assign",))
def _slice_assign(lhs, rhs, begin=(), end=(), step=None):
    return lhs.at[_slice_tuple(lhs.shape, begin, end, step)].set(rhs)


@register("_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=None):
    return data.at[_slice_tuple(data.shape, begin, end, step)].set(
        jnp.asarray(scalar, data.dtype))


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, shape=None):
    """Write rhs into a copy of lhs at gather_nd-style indices
    (reference indexing_op.cc _scatter_set_nd)."""
    idx = tuple(indices[i].astype(jnp.int64) for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    return lhs


@register("broadcast_like")
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    out_shape = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        out_shape[int(la)] = rhs.shape[int(ra)]
    return jnp.broadcast_to(lhs, tuple(out_shape))


@register("reshape_like")
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    if lhs_begin is None and rhs_begin is None:
        return jnp.reshape(lhs, rhs.shape)

    def _ax(v, ndim, default):
        if v is None:
            return default
        v = int(v)
        return v + ndim if v < 0 else v  # MXNet adds ndim: -1 == last axis

    lb = _ax(lhs_begin, lhs.ndim, 0)
    le = _ax(lhs_end, lhs.ndim, lhs.ndim)
    rb = _ax(rhs_begin, rhs.ndim, 0)
    re = _ax(rhs_end, rhs.ndim, rhs.ndim)
    new_shape = lhs.shape[:lb] + rhs.shape[rb:re] + lhs.shape[le:]
    return jnp.reshape(lhs, new_shape)


@register("_split_v2", n_out=-1)
def _split_v2(data, indices=(), axis=1, squeeze_axis=False, sections=0):
    """split_v2 (reference matrix_op.cc:1061): by section count or split
    indices."""
    if sections and sections > 0:
        parts = jnp.split(data, int(sections), axis=int(axis))
    else:
        parts = jnp.split(data, [int(i) for i in indices], axis=int(axis))
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=int(axis)) for p in parts]
    return tuple(parts)


@register("argmax_channel")
def argmax_channel(data):
    """argmax along the trailing axis, one index per leading row
    (reference broadcast_reduce_op_index.cc:82)."""
    return jnp.argmax(data, axis=-1).astype(data.dtype)


@register("add_n", aliases=("ElementWiseSum", "_element_wise_sum"))
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("moments", n_out=2)
def moments(data, axes=None, keepdims=False):
    axes = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=axes, keepdims=keepdims)
    if not keepdims:
        mean = jnp.reshape(mean, var.shape)
    return mean, var


@register("_square_sum", aliases=("square_sum",))
def _square_sum(data, axis=None, keepdims=False):
    return jnp.sum(jnp.square(data),
                   axis=tuple(axis) if isinstance(axis, (list, tuple))
                   else axis, keepdims=keepdims)


@register("cast_storage")
def cast_storage(data, stype=None):
    """Storage casts are identity on TPU: XLA has one dense layout engine
    (reference cast_storage-inl.h; sparse API docs in ndarray/sparse.py)."""
    return data


@register("_sparse_retain", aliases=("sparse_retain",))
def _sparse_retain(data, indices):
    """Keep only the given rows, zeroing the rest (row_sparse retain,
    reference sparse_retain-inl.h, dense result)."""
    mask = jnp.zeros((data.shape[0],), dtype=bool)
    mask = mask.at[indices.astype(jnp.int64)].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


@register("all_finite")
def all_finite(data, init_output=True):
    return jnp.all(jnp.isfinite(data)).reshape((1,))


@register("multi_all_finite")
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a)))
    return ok.reshape((1,))


@register("multi_sum_sq", n_out=-1)
def multi_sum_sq(*arrays, num_arrays=1):
    return tuple(jnp.sum(jnp.square(a)).reshape(()) for a in arrays)


@register("reset_arrays", n_out=-1)
def reset_arrays(*arrays, num_arrays=1):
    return tuple(jnp.zeros_like(a) for a in arrays)


@register("_rnn_param_concat")
def _rnn_param_concat(*args, dim=0, num_args=None):
    return jnp.concatenate(args, axis=int(dim))
