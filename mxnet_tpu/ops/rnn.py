"""Fused recurrent layers as lax.scan programs.

Role parity: reference ``src/operator/rnn-inl.h:414`` RNNOp (cuDNN fused
RNN/LSTM/GRU) and ``src/operator/rnn.cc``. TPU-native: one ``lax.scan`` over
time per layer/direction — the per-step i2h matmul is hoisted out of the
scan as a single big (T*B, I)x(I, G*H) MXU matmul, and only the h2h matmul
recurs inside the scan body; XLA pipelines the scan on-chip.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["rnn_scan_layer"]


def _gates_precompute(x, w_ih, b_ih):
    # x: (T, B, I) → (T, B, G*H) in one MXU matmul
    T, B, I = x.shape
    y = jnp.dot(x.reshape(T * B, I), w_ih.T)
    if b_ih is not None:
        y = y + b_ih
    return y.reshape(T, B, -1)


def _lstm_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0):
    """MXNet gate order: in, forget, cell, out (reference rnn-inl.h)."""
    gx = _gates_precompute(x, w_ih, b_ih)
    H = h0.shape[-1]

    def step(carry, g_t):
        h, c = carry
        gates = g_t + jnp.dot(h, w_hh.T) + (b_hh if b_hh is not None else 0)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), ys = lax.scan(step, (h0, c0), gx)
    return ys, hT, cT


def _gru_layer(x, w_ih, w_hh, b_ih, b_hh, h0):
    """MXNet gate order: reset, update, new (reference rnn-inl.h GRU)."""
    gx = _gates_precompute(x, w_ih, b_ih)

    def step(h, g_t):
        gh = jnp.dot(h, w_hh.T) + (b_hh if b_hh is not None else 0)
        xr, xz, xn = jnp.split(g_t, 3, axis=-1)
        hr, hz, hn = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h = (1 - z) * n + z * h
        return h, h

    hT, ys = lax.scan(step, h0, gx)
    return ys, hT


def _rnn_layer(x, w_ih, w_hh, b_ih, b_hh, h0, act):
    gx = _gates_precompute(x, w_ih, b_ih)
    actfn = jnp.tanh if act == "tanh" else jax.nn.relu

    def step(h, g_t):
        h = actfn(g_t + jnp.dot(h, w_hh.T) +
                  (b_hh if b_hh is not None else 0))
        return h, h

    hT, ys = lax.scan(step, h0, gx)
    return ys, hT


@register("_rnn_scan_layer", n_out=0)
def rnn_scan_layer(data, w_ih, w_hh, b_ih, b_hh, h0, c0=None,
                   mode="lstm", reverse=False):
    """One direction of one recurrent layer over a full (T, B, I) sequence.

    Returns (output (T,B,H), h_T, [c_T]). The Gluon layer composes
    multi-layer / bidirectional stacks from this primitive.
    """
    x = jnp.flip(data, axis=0) if reverse else data
    if mode == "lstm":
        ys, hT, cT = _lstm_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0)
        if reverse:
            ys = jnp.flip(ys, axis=0)
        return ys, hT, cT
    if mode == "gru":
        ys, hT = _gru_layer(x, w_ih, w_hh, b_ih, b_hh, h0)
    elif mode in ("rnn_tanh", "rnn_relu"):
        ys, hT = _rnn_layer(x, w_ih, w_hh, b_ih, b_hh, h0,
                            "tanh" if mode == "rnn_tanh" else "relu")
    else:
        raise ValueError("unknown RNN mode %s" % mode)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT
