"""Fused recurrent layers as lax.scan programs.

Role parity: reference ``src/operator/rnn-inl.h:414`` RNNOp (cuDNN fused
RNN/LSTM/GRU) and ``src/operator/rnn.cc``. TPU-native: one ``lax.scan`` over
time per layer/direction — the per-step i2h matmul is hoisted out of the
scan as a single big (T*B, I)x(I, G*H) MXU matmul, and only the h2h matmul
recurs inside the scan body; XLA pipelines the scan on-chip.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ._common import _bind_key, _bind_train
from .registry import register

__all__ = ["rnn_scan_layer", "RNN", "rnn_param_size"]


def _gates_precompute(x, w_ih, b_ih):
    # x: (T, B, I) → (T, B, G*H) in one MXU matmul
    T, B, I = x.shape
    y = jnp.dot(x.reshape(T * B, I), w_ih.T)
    if b_ih is not None:
        y = y + b_ih
    return y.reshape(T, B, -1)


def _lstm_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0):
    """MXNet gate order: in, forget, cell, out (reference rnn-inl.h)."""
    gx = _gates_precompute(x, w_ih, b_ih)
    H = h0.shape[-1]

    def step(carry, g_t):
        h, c = carry
        gates = g_t + jnp.dot(h, w_hh.T) + (b_hh if b_hh is not None else 0)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), ys = lax.scan(step, (h0, c0), gx)
    return ys, hT, cT


def _gru_layer(x, w_ih, w_hh, b_ih, b_hh, h0):
    """MXNet gate order: reset, update, new (reference rnn-inl.h GRU)."""
    gx = _gates_precompute(x, w_ih, b_ih)

    def step(h, g_t):
        gh = jnp.dot(h, w_hh.T) + (b_hh if b_hh is not None else 0)
        xr, xz, xn = jnp.split(g_t, 3, axis=-1)
        hr, hz, hn = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h = (1 - z) * n + z * h
        return h, h

    hT, ys = lax.scan(step, h0, gx)
    return ys, hT


def _rnn_layer(x, w_ih, w_hh, b_ih, b_hh, h0, act):
    gx = _gates_precompute(x, w_ih, b_ih)
    actfn = jnp.tanh if act == "tanh" else jax.nn.relu

    def step(h, g_t):
        h = actfn(g_t + jnp.dot(h, w_hh.T) +
                  (b_hh if b_hh is not None else 0))
        return h, h

    hT, ys = lax.scan(step, h0, gx)
    return ys, hT


@register("_rnn_scan_layer", n_out=0)
def rnn_scan_layer(data, w_ih, w_hh, b_ih, b_hh, h0, c0=None,
                   mode="lstm", reverse=False):
    """One direction of one recurrent layer over a full (T, B, I) sequence.

    Returns (output (T,B,H), h_T, [c_T]). The Gluon layer composes
    multi-layer / bidirectional stacks from this primitive.
    """
    x = jnp.flip(data, axis=0) if reverse else data
    if mode == "lstm":
        ys, hT, cT = _lstm_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0)
        if reverse:
            ys = jnp.flip(ys, axis=0)
        return ys, hT, cT
    if mode == "gru":
        ys, hT = _gru_layer(x, w_ih, w_hh, b_ih, b_hh, h0)
    elif mode in ("rnn_tanh", "rnn_relu"):
        ys, hT = _rnn_layer(x, w_ih, w_hh, b_ih, b_hh, h0,
                            "tanh" if mode == "rnn_tanh" else "relu")
    else:
        raise ValueError("unknown RNN mode %s" % mode)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT


# ------------------------------------------------------------- fused RNN op
# (reference src/operator/rnn-inl.h RNNOp / rnn.cc `RNN`: one op carrying a
# cuDNN-style flat parameter vector. Gate counts and the weights-then-biases
# flat layout follow GetRnnParamSize rnn-inl.h; gate orders match the scan
# layers above: LSTM i,f,g,o — GRU r,z,n.)

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional=False,
                   mode="lstm"):
    """Total flat-parameter length (reference rnn-inl.h GetRnnParamSize)."""
    G = _GATES[mode]
    D = 2 if bidirectional else 1
    size = G * state_size * D
    first = (input_size + state_size + 2) * size
    rest = (state_size * D + state_size + 2) * size
    return first + (num_layers - 1) * rest


def _split_rnn_params(params, num_layers, input_size, H, D, G):
    """Slice the flat vector into per-(layer, direction) weight/bias sets.

    Layout: all weights first (layer-major, direction-minor: i2h then h2h),
    then all biases in the same order — the cuDNN canonical order the
    reference packs into (rnn-inl.h).
    """
    off = 0
    weights = []
    for layer in range(num_layers):
        inp = input_size if layer == 0 else H * D
        per_dir = []
        for _ in range(D):
            w_ih = params[off:off + G * H * inp].reshape(G * H, inp)
            off += G * H * inp
            w_hh = params[off:off + G * H * H].reshape(G * H, H)
            off += G * H * H
            per_dir.append([w_ih, w_hh])
        weights.append(per_dir)
    for layer in range(num_layers):
        for d in range(D):
            b_ih = params[off:off + G * H]
            off += G * H
            b_hh = params[off:off + G * H]
            off += G * H
            weights[layer][d] += [b_ih, b_hh]
    return weights


@register("RNN", n_out=0, state_binders={"key": _bind_key,
                                         "train": _bind_train})
def RNN(data, parameters, state, state_cell=None, state_size=0,
        num_layers=1, bidirectional=False, mode="lstm", p=0.0,
        state_outputs=False, projection_size=None, key=None, train=False,
        **_ignored):
    """Fused multi-layer (bi)directional RNN/LSTM/GRU over (T, B, I) input.

    Inputs follow the reference op: ``data`` time-major (seq, batch, feat),
    ``parameters`` a flat vector (layout above), ``state`` (L*D, B, H), and
    ``state_cell`` for LSTM. Returns ``output`` (T, B, D*H) plus, when
    ``state_outputs``, the final h (and c for LSTM). Dropout ``p`` applies
    between layers in training, as in the reference (rnn-inl.h).
    """
    if projection_size not in (None, 0):
        raise NotImplementedError("LSTMP projection_size is not supported")
    mode = str(mode)
    G = _GATES[mode]
    D = 2 if bidirectional else 1
    H = int(state_size)
    L = int(num_layers)
    sets = _split_rnn_params(parameters, L, data.shape[2], H, D, G)

    x = data
    h_finals, c_finals = [], []
    for layer in range(L):
        outs = []
        for d in range(D):
            w_ih, w_hh, b_ih, b_hh = sets[layer][d]
            h0 = state[layer * D + d]
            xd = jnp.flip(x, axis=0) if d == 1 else x
            if mode == "lstm":
                c0 = state_cell[layer * D + d]
                ys, hT, cT = _lstm_layer(xd, w_ih, w_hh, b_ih, b_hh, h0, c0)
                c_finals.append(cT)
            elif mode == "gru":
                ys, hT = _gru_layer(xd, w_ih, w_hh, b_ih, b_hh, h0)
            else:
                ys, hT = _rnn_layer(xd, w_ih, w_hh, b_ih, b_hh, h0,
                                    "tanh" if mode == "rnn_tanh" else "relu")
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            h_finals.append(hT)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if train and p > 0.0 and layer < L - 1 and key is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(key, layer), 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))

    if not state_outputs:
        return (x,)
    hN = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        return x, hN, jnp.stack(c_finals, axis=0)
    return x, hN
