"""Linear-algebra operator breadth.

Role parity: the remaining registrations of reference
``src/operator/tensor/la_op.cc`` (det/slogdet/inverse/potri/trmm/gelqf/
syevd/makediag/maketrian/extracttrian) — lowered to jax.numpy.linalg /
lax.linalg where XLA provides blocked TPU kernels.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register, register_alias


@register("linalg_det", aliases=("_linalg_det", "det"))
def linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", aliases=("_linalg_slogdet",), n_out=2)
def linalg_slogdet(A):
    sign, logabsdet = jnp.linalg.slogdet(A)
    return sign, logabsdet


@register("linalg_inverse", aliases=("_linalg_inverse", "inverse"))
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("linalg_potri", aliases=("_linalg_potri",))
def linalg_potri(A):
    """Inverse from a Cholesky factor: (L L^T)^-1 (reference la_op.cc
    potri)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    Linv = lax.linalg.triangular_solve(A, eye, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


@register("linalg_trmm", aliases=("_linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matrix multiply B = alpha * op(A) B (reference trmm)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@register("linalg_gelqf", aliases=("_linalg_gelqf",), n_out=2)
def linalg_gelqf(A):
    """LQ factorization A = L Q with Q orthonormal rows (reference gelqf;
    computed via QR of A^T on TPU)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_syevd", aliases=("_linalg_syevd",), n_out=2)
def linalg_syevd(A):
    """Symmetric eigendecomposition A = U^T diag(L) U (reference syevd:
    rows of the returned U are the eigenvectors)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_makediag", aliases=("_linalg_makediag",))
def linalg_makediag(A, offset=0):
    return jnp.apply_along_axis(
        lambda d: jnp.diag(d, k=int(offset)), -1, A) \
        if A.ndim > 1 else jnp.diag(A, k=int(offset))


@register("linalg_maketrian", aliases=("_linalg_maketrian",))
def linalg_maketrian(A, offset=0, lower=True):
    """Pack a vector of triangle entries into a triangular matrix
    (reference maketrian, inverse of extracttrian): recover the matrix
    size n from the entry count, then scatter."""
    k = int(offset)
    n_entries = A.shape[-1]
    n = 1
    while len(_tri_indices(n, k, lower)[0]) < n_entries:
        n += 1
    rows, cols = _tri_indices(n, k, lower)
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., rows, cols].set(A)


def _tri_indices(n, k, lower):
    """offset 0: triangle chosen by `lower`; offset>0: triangle above the
    k-th superdiagonal; offset<0: below the k-th subdiagonal (reference
    la_op.h ExtractTrianParam semantics)."""
    import numpy as np
    if k > 0:
        return np.triu_indices(n, k)
    if k < 0:
        return np.tril_indices(n, k)
    return np.tril_indices(n) if lower else np.triu_indices(n)


@register("linalg_extracttrian", aliases=("_linalg_extracttrian",))
def linalg_extracttrian(A, offset=0, lower=True):
    rows, cols = _tri_indices(A.shape[-1], int(offset), lower)
    return A[..., rows, cols]


register_alias("linalg_gemm", "_linalg_gemm")
register_alias("linalg_gemm2", "_linalg_gemm2")
register_alias("linalg_potrf", "_linalg_potrf")
register_alias("linalg_syrk", "_linalg_syrk")
register_alias("linalg_trsm", "_linalg_trsm")
register_alias("linalg_sumlogdiag", "_linalg_sumlogdiag")
register_alias("linalg_extractdiag", "_linalg_extractdiag")
