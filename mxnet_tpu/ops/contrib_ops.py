"""Contrib + control-flow operators.

Role parity: reference ``src/operator/contrib/control_flow.cc``
(_foreach :1089, _while_loop, _cond :1255) and assorted contrib ops.
TPU-native: control flow maps directly onto lax.scan / lax.while_loop /
lax.cond — compiler-friendly structured control flow instead of the
reference's subgraph-executor machinery.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# These take Python callables over NDArray handles; used by mx.nd.contrib.*
# wrappers in ndarray/__init__ (they are not tape ops — jax traces through).

def foreach(body, data, init_states):
    """reference `python/mxnet/ndarray/contrib.py` foreach →
    `src/operator/contrib/control_flow.cc:1089`. Maps to lax.scan."""
    from ..ndarray.ndarray import NDArray

    single_data = isinstance(data, NDArray)
    single_state = isinstance(init_states, NDArray)
    data_t = [data] if single_data else list(data)
    states = [init_states] if single_state else list(init_states)

    def step(carry, xs):
        nd_xs = [NDArray(x) for x in xs]
        nd_carry = [NDArray(c) for c in carry]
        out, new_states = body(nd_xs[0] if single_data else nd_xs,
                               nd_carry[0] if single_state else nd_carry)
        out_l = [out] if isinstance(out, NDArray) else list(out)
        ns_l = [new_states] if isinstance(new_states, NDArray) else list(new_states)
        return tuple(s._data for s in ns_l), tuple(o._data for o in out_l)

    carry, ys = lax.scan(step, tuple(s._data for s in states),
                         tuple(d._data for d in data_t))
    outs = [NDArray(y) for y in ys]
    final = [NDArray(c) for c in carry]
    return (outs[0] if len(outs) == 1 else outs,
            final[0] if single_state else final)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """reference contrib while_loop → lax.while_loop (no max_iterations
    unrolling needed; XLA handles dynamic trip count)."""
    from ..ndarray.ndarray import NDArray
    single = isinstance(loop_vars, NDArray)
    lv = [loop_vars] if single else list(loop_vars)

    def jcond(vals):
        return cond(*[NDArray(v) for v in vals])._data.astype(bool).reshape(())

    def jbody(vals):
        res = func(*[NDArray(v) for v in vals])
        res = [res] if isinstance(res, NDArray) else list(res)
        return tuple(r._data for r in res)

    out = lax.while_loop(jcond, jbody, tuple(v._data for v in lv))
    outs = [NDArray(v) for v in out]
    return outs[0] if single else outs


def cond(pred, then_func, else_func, inputs=None):
    """reference contrib cond → lax.cond."""
    from ..ndarray.ndarray import NDArray
    p = pred._data.astype(bool).reshape(()) if isinstance(pred, NDArray) else pred

    def _norm(f):
        def g(_):
            res = f()
            rl = [res] if isinstance(res, NDArray) else list(res)
            return tuple(r._data for r in rl)
        return g

    out = lax.cond(p, _norm(then_func), _norm(else_func), operand=None)
    outs = [NDArray(v) for v in out]
    return outs[0] if len(outs) == 1 else outs


@register("_contrib_arange_like", differentiable=False)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = 1
        for s in data.shape:
            n *= s
        out = start + step * jnp.arange(n, dtype=data.dtype)
        return out.reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n, dtype=data.dtype)


@register("_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    import numpy as _np
    return data / _np.sqrt(data.shape[-1]).astype(data.dtype)


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


# _contrib_boolean_mask lives in detection_ops.py (eager-only with a
# clear dynamic-shape error under tracing).
