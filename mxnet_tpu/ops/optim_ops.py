"""Optimizer update kernels as framework ops.

Role parity: reference ``src/operator/optimizer_op.cc`` (sgd/adam/ftrl/...
update ops invoked by python optimizers) and ``contrib/adamw.cc``. Each op
is a pure functional update returning the new weight (and new state tensors
where the reference writes them in-place) — callers rebind, and under jit
XLA turns the rebind into an in-place donated-buffer update (the same
mechanism `optimizer/optimizer.py` uses for its fused trainer kernels).

Gradient clipping/rescale semantics follow the reference: grad is first
scaled by rescale_grad, then clipped, then weight decay applied.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update")
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", n_out=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("mp_sgd_update", n_out=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Mixed-precision sgd: fp32 master weight, low-precision model weight
    (reference optimizer_op.cc MP_SGD)."""
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * (g + wd * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", n_out=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("nag_mom_update", n_out=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov momentum (reference optimizer_op.cc NAG)."""
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("mp_nag_mom_update", n_out=3)
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient) \
        + wd * weight32
    new_mom = momentum * mom + g
    new_w32 = weight32 - lr * (g + momentum * new_mom)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", n_out=3)
def adam_update(weight, grad, mean, var, lr=0.01, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("_adamw_update", aliases=("adamw_update",), n_out=3)
def _adamw_update(weight, grad, mean, var, rescale_grad, lr=0.01, beta1=0.9,
                  beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                  clip_gradient=-1.0):
    """AdamW: decoupled weight decay (reference contrib/adamw.cc; tensor
    rescale_grad input carries the dynamic loss scale)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                            + wd * weight)
    return new_w, new_mean, new_var


@register("_mp_adamw_update", aliases=("mp_adamw_update",), n_out=4)
def _mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad,
                     lr=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                     eta=1.0, clip_gradient=-1.0):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w32 = weight32 - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                                + wd * weight32)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


@register("ftml_update", n_out=4)
def ftml_update(weight, grad, d, v, z, lr=0.01, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0,
                clip_gradient=-1.0, t=1):
    """FTML (reference optimizer_op.cc FTMLUpdate)."""
    clip = clip_gradient if clip_gradient is not None and clip_gradient >= 0 \
        else clip_grad
    g = _prep(grad, rescale_grad, clip) + wd * weight
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    t = float(t)
    denom = 1 - beta1 ** t
    d_t = denom / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return new_w, d_t, new_v, new_z


@register("ftrl_update", n_out=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    """FTRL-proximal (reference optimizer_op.cc FtrlUpdate)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        (jnp.sign(new_z) * lamda1 - new_z)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register("rmsprop_update", n_out=2)
def rmsprop_update(weight, grad, n, lr=0.01, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", n_out=4)
def rmspropalex_update(weight, grad, n, g, delta, lr=0.01, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """RMSProp with Alex Graves' centering (reference rmspropalex)."""
    gr = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(gr) + gamma1 * n
    new_g = (1 - gamma1) * gr + gamma1 * g
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("signsgd_update")
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", n_out=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom - (1 - momentum) * g
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("_sparse_adagrad_update", aliases=("adagrad_update",), n_out=2)
def _sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7,
                           wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_hist = history + jnp.square(g)
    new_w = weight - lr * (g / (jnp.sqrt(new_hist) + epsilon) + wd * weight)
    return new_w, new_hist


@register("multi_lars")
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0):
    """Per-layer LARS coefficients (reference optimizer_op.cc MultiLARS):
    lr_i * ratio where ratio = eta*||w|| / (||g||*rescale + wd*||w|| + eps)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = jnp.where(
        (w_norm > 0) & (g_norm > 0),
        eta * w_norm / (g_norm + wds * w_norm + eps),
        jnp.ones_like(w_norm))
    return lrs * ratio


def _seq(v, i, default):
    if v is None:
        return default
    try:
        return float(v[i])
    except (TypeError, IndexError):
        return float(v)


@register("multi_sgd_update", n_out=-1)
def multi_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1):
    outs = []
    for i in range(int(num_weights)):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        outs.append(sgd_update.fn(w, g, lr=_seq(lrs, i, 0.01),
                                  wd=_seq(wds, i, 0.0),
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient))
    return tuple(outs)


@register("multi_sgd_mom_update", n_out=-1)
def multi_sgd_mom_update(*arrays, lrs=None, wds=None, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1):
    outs = []
    for i in range(int(num_weights)):
        w, g, m = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        new_w, new_m = sgd_mom_update.fn(
            w, g, m, lr=_seq(lrs, i, 0.01), momentum=momentum,
            wd=_seq(wds, i, 0.0), rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
        outs.extend([new_w, new_m])
    return tuple(outs)


@register("multi_mp_sgd_update", n_out=-1)
def multi_mp_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=1):
    outs = []
    for i in range(int(num_weights)):
        w, g, w32 = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        new_w, new_w32 = mp_sgd_update.fn(
            w, g, w32, lr=_seq(lrs, i, 0.01), wd=_seq(wds, i, 0.0),
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        outs.extend([new_w, new_w32])
    return tuple(outs)


@register("multi_mp_sgd_mom_update", n_out=-1)
def multi_mp_sgd_mom_update(*arrays, lrs=None, wds=None, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=1):
    outs = []
    for i in range(int(num_weights)):
        w, g, m, w32 = arrays[4 * i:4 * i + 4]
        new_w, new_m, new_w32 = mp_sgd_mom_update.fn(
            w, g, m, w32, lr=_seq(lrs, i, 0.01), momentum=momentum,
            wd=_seq(wds, i, 0.0), rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
        outs.extend([new_w, new_m, new_w32])
    return tuple(outs)


@register("_multi_adamw_update", aliases=("multi_adamw_update",), n_out=-1)
def _multi_adamw_update(*arrays, lrs=None, wds=None, etas=None, beta1=0.9,
                        beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                        num_weights=1):
    rescale = arrays[-1]
    outs = []
    for i in range(int(num_weights)):
        w, g, m, v = arrays[4 * i:4 * i + 4]
        new_w, new_m, new_v = _adamw_update.fn(
            w, g, m, v, rescale, lr=_seq(lrs, i, 0.01),
            beta1=beta1, beta2=beta2, epsilon=epsilon,
            wd=_seq(wds, i, 0.0), eta=_seq(etas, i, 1.0),
            clip_gradient=clip_gradient)
        outs.extend([new_w, new_m, new_v])
    return tuple(outs)


@register("_multi_mp_adamw_update", aliases=("multi_mp_adamw_update",),
          n_out=-1)
def _multi_mp_adamw_update(*arrays, lrs=None, wds=None, etas=None, beta1=0.9,
                           beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                           num_weights=1):
    rescale = arrays[-1]
    outs = []
    for i in range(int(num_weights)):
        w, g, m, v, w32 = arrays[5 * i:5 * i + 5]
        new_w, new_m, new_v, new_w32 = _mp_adamw_update.fn(
            w, g, m, v, w32, rescale, lr=_seq(lrs, i, 0.01),
            beta1=beta1, beta2=beta2, epsilon=epsilon,
            wd=_seq(wds, i, 0.0), eta=_seq(etas, i, 1.0),
            clip_gradient=clip_gradient)
        outs.extend([new_w, new_m, new_v, new_w32])
    return tuple(outs)


# preloaded_* variants: lrs/wds arrive as tensors instead of attrs
# (reference contrib/preloaded_multi_sgd.cc) — tensor layout:
# [w0, g0, (m0,) (w32_0,) ..., lrs, wds]
def _preloaded(step, mom, mp):
    def run(*arrays, rescale_grad=1.0, clip_gradient=-1.0, num_weights=1):
        num_weights = int(num_weights)
        lrs, wds = arrays[-2], arrays[-1]
        body = arrays[:-2]
        outs = []
        for i in range(num_weights):
            group = body[step * i:step * (i + 1)]
            lr, wd = lrs[i], wds[i]
            if not mom and not mp:
                outs.append(sgd_update.fn(
                    group[0], group[1], lr=lr, wd=wd,
                    rescale_grad=rescale_grad, clip_gradient=clip_gradient))
            elif mom and not mp:
                outs.extend(sgd_mom_update.fn(
                    group[0], group[1], group[2], lr=lr, wd=wd,
                    rescale_grad=rescale_grad, clip_gradient=clip_gradient))
            elif not mom and mp:
                outs.extend(mp_sgd_update.fn(
                    group[0], group[1], group[2], lr=lr, wd=wd,
                    rescale_grad=rescale_grad, clip_gradient=clip_gradient))
            else:
                outs.extend(mp_sgd_mom_update.fn(
                    group[0], group[1], group[2], group[3], lr=lr, wd=wd,
                    rescale_grad=rescale_grad, clip_gradient=clip_gradient))
        return tuple(outs)
    return run


register("preloaded_multi_sgd_update", n_out=-1)(_preloaded(2, False, False))
register("preloaded_multi_sgd_mom_update", n_out=-1)(
    _preloaded(3, True, False))
register("preloaded_multi_mp_sgd_update", n_out=-1)(
    _preloaded(3, False, True))
register("preloaded_multi_mp_sgd_mom_update", n_out=-1)(
    _preloaded(4, True, True))
