"""Spatial-transform and signal ops.

Role parity: reference legacy operators ``src/operator/grid_generator-inl.h``
(GridGenerator), ``bilinear_sampler-inl.h`` (BilinearSampler),
``spatial_transformer-inl.h`` (SpatialTransformer), ``crop-inl.h`` (Crop),
``svm_output-inl.h`` (SVMOutput one-vs-all hinge gradients),
``correlation-inl.h`` (FlowNet Correlation), and contrib signal ops
``contrib/fft-inl.h`` / ``ifft-inl.h`` (interleaved-complex 1D FFT) and
``contrib/count_sketch-inl.h``; plus ``contrib/sync_batch_norm-inl.h``
(SyncBatchNorm — on TPU the cross-device reduction is a ``lax.pmean`` over
the data-parallel mesh axis instead of the reference's host-side barrier).

All sampling math is expressed as gathers + piecewise-linear weights so XLA
fuses it and JAX autodiff produces the data/grid gradients the reference
hand-writes.
"""
from __future__ import annotations

from functools import partial as _partial

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["GridGenerator", "BilinearSampler", "SpatialTransformer", "Crop",
           "SVMOutput", "Correlation", "fft", "ifft", "count_sketch",
           "SyncBatchNorm"]


# ------------------------------------------------------------ grid + sample

def _affine_grid(theta, H, W):
    """(B, 6) affine -> (B, 2, H, W) source coords in [-1, 1], channel 0 = x."""
    B = theta.shape[0]
    th = theta.reshape(B, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, H, dtype=th.dtype)
    xs = jnp.linspace(-1.0, 1.0, W, dtype=th.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    tgt = jnp.stack([gx.ravel(), gy.ravel(), jnp.ones(H * W, th.dtype)])
    src = jnp.einsum("bij,jk->bik", th, tgt)  # (B, 2, H*W)
    return src.reshape(B, 2, H, W)


@register("GridGenerator", aliases=("grid_generator",))
def GridGenerator(data, transform_type="affine", target_shape=(0, 0)):
    H, W = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        return _affine_grid(data, H, W)
    if transform_type == "warp":
        # data = (B, 2, H, W) pixel-space flow added to the identity grid,
        # then normalized to [-1, 1]
        B, _, Hf, Wf = data.shape
        gy, gx = jnp.meshgrid(jnp.arange(Hf, dtype=data.dtype),
                              jnp.arange(Wf, dtype=data.dtype),
                              indexing="ij")
        x = (gx + data[:, 0]) * (2.0 / max(Wf - 1, 1)) - 1.0
        y = (gy + data[:, 1]) * (2.0 / max(Hf - 1, 1)) - 1.0
        return jnp.stack([x, y], axis=1)
    raise ValueError("unknown transform_type %r" % (transform_type,))


def _sample_one(img, gx, gy):
    """img (C, H, W); gx/gy (Ho, Wo) absolute pixel coords. Zero padding."""
    C, H, W = img.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    out = jnp.zeros((C,) + gx.shape, img.dtype)
    for dy in (0.0, 1.0):
        for dx in (0.0, 1.0):
            xi, yi = x0 + dx, y0 + dy
            w = (1.0 - jnp.abs(gx - xi)) * (1.0 - jnp.abs(gy - yi))
            valid = ((xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1))
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            out = out + jnp.where(valid, w, 0.0) * img[:, yc, xc]
    return out


@register("BilinearSampler", aliases=("bilinear_sampler",))
def BilinearSampler(data, grid, cudnn_off=False):
    """Sample ``data`` (B,C,H,W) at ``grid`` (B,2,Ho,Wo) normalized coords;
    x = -1 maps to column 0, x = +1 to column W-1, outside -> 0."""
    _, _, H, W = data.shape
    gx = (grid[:, 0] + 1.0) * ((W - 1) / 2.0)
    gy = (grid[:, 1] + 1.0) * ((H - 1) / 2.0)
    return jax.vmap(_sample_one)(data, gx, gy)


@register("SpatialTransformer", aliases=("spatial_transformer",))
def SpatialTransformer(data, loc, target_shape=(0, 0),
                       transform_type="affine", sampler_type="bilinear",
                       cudnn_off=False):
    grid = _affine_grid(loc, int(target_shape[0]), int(target_shape[1]))
    return BilinearSampler.fn(data, grid)


@register("Crop", aliases=("crop_v1",), n_out=1)
def Crop(data, crop_like=None, offset=(0, 0), h_w=(0, 0),
         center_crop=False, num_args=1):
    """Spatial crop of (B,C,H,W) to ``crop_like``'s H/W or explicit h_w."""
    _, _, H, W = data.shape
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy:oy + th, ox:ox + tw]


# ------------------------------------------------------------------ SVM head

@_partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_run(data, label, margin, reg, linear):
    return data


def _svm_fwd(data, label, margin, reg, linear):
    return data, (data, label)


def _svm_bwd(margin, reg, linear, res, g):
    z, label = res
    k = jax.nn.one_hot(label.astype(jnp.int32), z.shape[1], dtype=z.dtype)
    if linear:
        pos = -reg * (margin > z).astype(z.dtype)          # true class
        neg = reg * (margin > -z).astype(z.dtype)          # other classes
    else:
        pos = -reg * 2.0 * jnp.maximum(margin - z, 0.0)
        neg = reg * 2.0 * jnp.maximum(margin + z, 0.0)
    grad = k * pos + (1.0 - k) * neg
    return grad.astype(z.dtype), jnp.zeros(label.shape, z.dtype)


_svm_run.defvjp(_svm_fwd, _svm_bwd)


@register("SVMOutput", aliases=("svm_output",))
def SVMOutput(data, label, margin=1.0, regularization_coefficient=1.0,
              use_linear=False):
    """Forward passes scores through; backward injects the one-vs-all hinge
    gradient (reference svm_output.cc L1_SVM/L2_SVM kernels)."""
    return _svm_run(data, label, float(margin),
                    float(regularization_coefficient), bool(use_linear))


# --------------------------------------------------------------- correlation

@register("Correlation", aliases=("correlation",))
def Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer: for every displacement on a stride2 grid,
    the channel-mean (product | abs-diff) between kernel windows of the two
    feature maps. One fused reduce_window per displacement — a static
    D*D-step Python loop XLA unrolls into parallel window reductions."""
    B, C, H, W = data1.shape
    K = int(kernel_size)
    rad = (K - 1) // 2
    md, s1, s2, pad = (int(max_displacement), int(stride1), int(stride2),
                       int(pad_size))
    D = 2 * (md // s2) + 1
    border = md + rad
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = H + 2 * pad, W + 2 * pad
    out_h = -(-(Hp - 2 * border) // s1)
    out_w = -(-(Wp - 2 * border) // s1)
    norm = float(K * K * C)

    maps = []
    for iy in range(-(md // s2), md // s2 + 1):
        dy = iy * s2
        for ix in range(-(md // s2), md // s2 + 1):
            dx = ix * s2
            shifted = jnp.roll(p2, shift=(-dy, -dx), axis=(2, 3))
            prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
            summed = jnp.sum(prod, axis=1, keepdims=False)  # (B, Hp, Wp)
            if K > 1:
                summed = lax.reduce_window(
                    summed, jnp.asarray(0.0, summed.dtype), lax.add,
                    (1, K, K), (1, 1, 1), "SAME")
            win = summed[:, border:border + out_h * s1:s1,
                         border:border + out_w * s1:s1]
            maps.append(win / norm)
    return jnp.stack(maps, axis=1)  # (B, D*D, out_h, out_w)


# -------------------------------------------------------------- signal ops

@register("_contrib_fft", aliases=("fft",))
def fft(data, compute_size=128):
    """1D FFT over the last axis; complex output interleaved [re, im, ...]
    (reference contrib/fft-inl.h cuFFT C2C layout)."""
    spec = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(
        data.dtype)


@register("_contrib_ifft", aliases=("ifft",))
def ifft(data, compute_size=128):
    """Inverse of ``fft`` on interleaved-complex input; like the reference's
    cuFFT path the transform is UNNORMALIZED (ifft(fft(x)) == d * x)."""
    d = data.shape[-1] // 2
    inter = data.astype(jnp.float32).reshape(data.shape[:-1] + (d, 2))
    spec = lax.complex(inter[..., 0], inter[..., 1])
    out = jnp.fft.ifft(spec, axis=-1).real * d
    return out.astype(data.dtype)


@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection: out[b, h[i]] += s[i] * data[b, i]
    (reference contrib/count_sketch-inl.h). One scatter-add per batch row
    via segment_sum — XLA lowers it to a vectorized scatter."""
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    n_out = int(out_dim)

    def one(row):
        return jax.ops.segment_sum(row * sign, idx, num_segments=n_out)

    return jax.vmap(one)(data)


# ----------------------------------------------------------- SyncBatchNorm

@register("_contrib_SyncBatchNorm", aliases=("SyncBatchNorm",), n_out=0)
def SyncBatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                  momentum=0.9, fix_gamma=True, use_global_stats=False,
                  output_mean_var=False, ndev=1, key="", comm_axis="dp",
                  **_ignored):
    """BatchNorm whose batch statistics are averaged across the data-parallel
    mesh axis (reference contrib/sync_batch_norm-inl.h uses a host barrier +
    shared buffer; here the sync is a ``lax.pmean`` that XLA lowers to an
    ICI AllReduce when tracing under shard_map/pjit with a ``dp`` axis —
    outside any mesh context it's plain single-device BatchNorm)."""
    sh = (1, -1) + (1,) * (data.ndim - 2)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if use_global_stats:
        mean, var = moving_mean, moving_var
    else:
        axes = (0,) + tuple(range(2, data.ndim))
        mean = jnp.mean(data, axis=axes)
        sq = jnp.mean(jnp.square(data), axis=axes)
        try:
            mean = lax.pmean(mean, comm_axis)
            sq = lax.pmean(sq, comm_axis)
        except NameError:
            pass  # not under a mesh with that axis: local stats
        var = sq - jnp.square(mean)
    out = (data - mean.reshape(sh)) * (
        g.reshape(sh) / jnp.sqrt(var.reshape(sh) + eps)) + beta.reshape(sh)
    if output_mean_var:
        return out, mean, var
    return (out,)
