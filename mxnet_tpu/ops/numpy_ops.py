"""NumPy-semantics operator registrations (_npi_* / _np_* / _npx_*).

Role parity: reference ``src/operator/numpy/`` (16K LoC of np_* kernels
behind the mx.np/mx.npx frontends). Most are aliases onto the existing
jnp-backed corpus (which already has numpy semantics); the rest register
here. Value-dependent-shape ops (nonzero, unique, boolean indexing) work
eagerly on concrete arrays but cannot be traced under jit — the same
limitation the reference documents for their use inside hybridized
blocks.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import dtype_np
from ._common import _bind_key, _RNG, _dt  # noqa: F401
from .registry import register, register_alias








# ------------------------------------------------------------- new ops

@register("around", aliases=("_npi_around",))
def around(x, decimals=0):
    return jnp.round(x, int(decimals))


@register("nonzero", aliases=("_npi_nonzero", "_npx_nonzero"),
          differentiable=False)
def nonzero(x):
    """Indices of nonzero elements, (N, ndim) int64 (reference
    np_nonzero_op.cc). Eager-only: output shape is value-dependent."""
    idx = _np.nonzero(_np.asarray(x))
    return jnp.stack([jnp.asarray(i, jnp.int64) for i in idx], axis=-1)


@register("rot90", aliases=("_npi_rot90",))
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, int(k), tuple(int(a) for a in axes))


@register("std", aliases=("_npi_std",))
def std(x, axis=None, dtype=None, ddof=0, keepdims=False):
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    out = jnp.std(x, axis=axis, ddof=int(ddof), keepdims=keepdims)
    return out.astype(dtype_np(dtype)) if dtype is not None else out


@register("var", aliases=("_npi_var",))
def var(x, axis=None, dtype=None, ddof=0, keepdims=False):
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    out = jnp.var(x, axis=axis, ddof=int(ddof), keepdims=keepdims)
    return out.astype(dtype_np(dtype)) if dtype is not None else out


@register("unique", aliases=("_npi_unique",), differentiable=False,
          n_out=-1)
def unique(x, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    """Eager-only (value-dependent output shape), like the reference's
    np_unique_op.cc."""
    res = _np.unique(_np.asarray(x), return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


@register("_npi_svd", aliases=("svd",), n_out=3)
def _npi_svd(A):
    """gesvd returning (UT, L, V) in the reference's layout
    (np_linalg svd: A = u @ diag(s) @ vh).

    TPU has no native SVD lowering (libtpu aborts compiling the QR-sweep
    expansion through this image's AOT helper), so off-CPU the
    decomposition runs on the host via ``pure_callback`` — the same move
    the reference makes routing gesvd to LAPACK when the device lacks a
    solver (`src/operator/tensor/la_op.h` CPU path). Host path is
    forward-only (no custom VJP), matching the reference's
    no-backward-for-gesvd contract on non-LAPACK devices."""
    try:
        on_accel = any(d.platform not in ("cpu",) for d in jax.devices())
    except RuntimeError:
        on_accel = False
    if not on_accel:
        u, s, vh = jnp.linalg.svd(A, full_matrices=False)
        return u, s, vh

    import numpy as onp
    from jax.core import Tracer
    if isinstance(A, Tracer):
        # host callbacks are also unsupported through this image's PJRT
        # tunnel, so the host route only exists eagerly
        raise NotImplementedError(
            "svd inside jit is unsupported on TPU (no device solver, no "
            "host callback); call it eagerly or on a CPU context")
    dt = onp.dtype(onp.asarray(A).dtype)
    u, s, vh = onp.linalg.svd(onp.ascontiguousarray(A), full_matrices=False)
    return (jnp.asarray(u.astype(dt)), jnp.asarray(s.astype(dt)),
            jnp.asarray(vh.astype(dt)))


@register("einsum", aliases=("_npi_einsum",))
def einsum(*operands, subscripts="", optimize=0):
    return jnp.einsum(subscripts, *operands,
                      optimize="optimal" if optimize else "auto")


@register("tensordot", aliases=("_npi_tensordot",))
def tensordot(a, b, a_axes_summed=None, b_axes_summed=None, axes=None):
    if a_axes_summed is not None:
        return jnp.tensordot(a, b, axes=(tuple(a_axes_summed),
                                         tuple(b_axes_summed)))
    return jnp.tensordot(a, b, axes=2 if axes is None else axes)


@register("_npi_tensordot_int_axes")
def _npi_tensordot_int_axes(a, b, axes=2):
    return jnp.tensordot(a, b, axes=int(axes))


@register("diff", aliases=("_npi_diff",))
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=int(n), axis=int(axis))


@register("copysign", aliases=("_npi_copysign",))
def copysign(x1, x2):
    return jnp.copysign(x1, x2)


@register("_npi_copysign_scalar")
def _npi_copysign_scalar(x, scalar=1.0):
    return jnp.copysign(x, scalar)


@register("_npi_rcopysign_scalar")
def _npi_rcopysign_scalar(x, scalar=1.0):
    return jnp.copysign(jnp.asarray(scalar, x.dtype), x)


@register("lcm", aliases=("_npi_lcm",))
def lcm(x1, x2):
    return jnp.lcm(x1, x2)


@register("_npi_lcm_scalar")
def _npi_lcm_scalar(x, scalar=1):
    return jnp.lcm(x, jnp.asarray(int(scalar), x.dtype))


@register("ldexp", aliases=("_npi_ldexp",))
def ldexp(x1, x2):
    return jnp.ldexp(x1, x2.astype(jnp.int32))


@register("_npi_ldexp_scalar")
def _npi_ldexp_scalar(x, scalar=0):
    return jnp.ldexp(x, int(scalar))


@register("_npi_rldexp_scalar")
def _npi_rldexp_scalar(x, scalar=1.0):
    return jnp.ldexp(jnp.asarray(scalar, x.dtype), x.astype(jnp.int32))


@register("arctan2", aliases=("_npi_arctan2",))
def arctan2(x1, x2):
    return jnp.arctan2(x1, x2)


@register("_npi_arctan2_scalar")
def _npi_arctan2_scalar(x, scalar=0.0):
    return jnp.arctan2(x, jnp.asarray(scalar, x.dtype))


@register("_npi_rarctan2_scalar")
def _npi_rarctan2_scalar(x, scalar=0.0):
    return jnp.arctan2(jnp.asarray(scalar, x.dtype), x)


@register("nan_to_num", aliases=("_npi_nan_to_num",))
def nan_to_num(x, copy=True, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@register("_npi_indices", aliases=("indices",))
def _npi_indices(dimensions=(), dtype=None, ctx=None):
    return jnp.indices(tuple(int(d) for d in dimensions),
                       dtype=_dt(dtype, _np.int32))


@register("logspace", aliases=("_npi_logspace",))
def logspace(start=0.0, stop=1.0, num=50, endpoint=True, base=10.0,
             ctx=None, dtype=None):
    return jnp.logspace(start, stop, int(num), endpoint=endpoint,
                        base=base, dtype=_dt(dtype))


@register("_npi_blackman", aliases=("blackman",))
def _npi_blackman(M=0, ctx=None, dtype=None):
    return jnp.blackman(int(M)).astype(_dt(dtype))


@register("_npi_hamming", aliases=("hamming",))
def _npi_hamming(M=0, ctx=None, dtype=None):
    return jnp.hamming(int(M)).astype(_dt(dtype))


@register("_npi_hanning", aliases=("hanning",))
def _npi_hanning(M=0, ctx=None, dtype=None):
    return jnp.hanning(int(M)).astype(_dt(dtype))


@register("column_stack", aliases=("_npi_column_stack",))
def column_stack(*data, num_args=None):
    return jnp.column_stack(data)


@register("dstack", aliases=("_npi_dstack",))
def dstack(*data, num_args=None):
    return jnp.dstack(data)


@register("vstack", aliases=("_npi_vstack",))
def vstack(*data, num_args=None):
    return jnp.vstack(data)


@register("_npi_hsplit", n_out=-1)
def _npi_hsplit(x, indices=(), sections=0, axis=None, squeeze_axis=False):
    if sections:
        return tuple(jnp.split(x, int(sections), axis=1 if x.ndim > 1
                               else 0))
    return tuple(jnp.split(x, [int(i) for i in indices],
                           axis=1 if x.ndim > 1 else 0))


@register("tril", aliases=("_npi_tril",))
def tril(x, k=0):
    return jnp.tril(x, int(k))


@register("moveaxis", aliases=("_np_moveaxis",))
def moveaxis(x, source=0, destination=0):
    src = tuple(source) if isinstance(source, (list, tuple)) else int(source)
    dst = tuple(destination) if isinstance(destination, (list, tuple)) \
        else int(destination)
    return jnp.moveaxis(x, src, dst)


@register("trace", aliases=("_np_trace",))
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, int(offset), int(axis1), int(axis2))


@register("_npi_identity")
def _npi_identity(n=0, ctx=None, dtype=None):
    return jnp.eye(int(n), dtype=_dt(dtype))


@register("share_memory", aliases=("_npi_share_memory",),
          differentiable=False)
def share_memory(a, b):
    """Whether two arrays may share memory — always False across jax
    functional arrays (reference np_memory_op.cc)."""
    return jnp.zeros((), dtype=bool)


@register("_npi_boolean_mask_assign_scalar")
def _npi_boolean_mask_assign_scalar(data, mask, value=0.0):
    return jnp.where(mask.astype(bool), jnp.asarray(value, data.dtype),
                     data)


@register("_npi_boolean_mask_assign_tensor")
def _npi_boolean_mask_assign_tensor(data, mask, value):
    """Eager-only when value must be scattered by mask count; supports
    broadcastable value tensors directly."""
    m = mask.astype(bool)
    if value.shape == data.shape:
        return jnp.where(m, value, data)
    flat_idx = _np.nonzero(_np.asarray(m).ravel())[0]
    flat = data.ravel()
    flat = flat.at[jnp.asarray(flat_idx)].set(value.ravel())
    return flat.reshape(data.shape)


@register("_npi_bernoulli", differentiable=False, state_binders=_RNG)
def _npi_bernoulli(prob=None, logit=None, size=None, ctx=None, dtype=None,
                   key=None):
    if prob is None and logit is None:
        prob = 0.5
    elif prob is None:
        prob = jax.nn.sigmoid(jnp.asarray(logit))
    shape = tuple(size) if size is not None else jnp.shape(prob)
    out = jax.random.bernoulli(key, prob, shape)
    return out.astype(_dt(dtype))


@register("_npi_choice", differentiable=False, state_binders=_RNG)
def _npi_choice(a=None, size=None, replace=True, p=None, ctx=None,
                key=None, weights=None):
    n = int(a) if not hasattr(a, "shape") else a.shape[0]
    shape = tuple(size or ())
    pool = jnp.arange(n) if not hasattr(a, "shape") else a
    probs = p if p is not None else weights
    return jax.random.choice(key, pool, shape, replace=bool(replace),
                             p=probs)


@register("_npi_multinomial", differentiable=False, state_binders=_RNG)
def _npi_multinomial(n=1, pvals=None, size=None, key=None):
    """np.random.multinomial: draw counts over categories (reference
    np_multinomial_op.h)."""
    k = pvals.shape[-1] if hasattr(pvals, "shape") else len(pvals)
    p = jnp.asarray(pvals)
    shape = tuple(size or ()) + (int(n),)
    draws = jax.random.categorical(key, jnp.log(jnp.maximum(p, 1e-30)),
                                   shape=shape)
    counts = jax.nn.one_hot(draws, k, dtype=jnp.int64).sum(axis=-2)
    return counts


# ------------------------------------------------------------- aliases

_NPI_ALIASES = {
    "abs": ("_npi_abs", "_npi_absolute"),
    "add": ("_npi_add",),
    "_plus_scalar": ("_npi_add_scalar",),
    "subtract": ("_npi_subtract",),
    "_minus_scalar": ("_npi_subtract_scalar",),
    "_rminus_scalar": ("_npi_rsubtract_scalar",),
    "multiply": ("_npi_multiply",),
    "_mul_scalar": ("_npi_multiply_scalar",),
    "divide": ("_npi_true_divide",),
    "_div_scalar": ("_npi_true_divide_scalar",),
    "_rdiv_scalar": ("_npi_rtrue_divide_scalar",),
    "mod": ("_npi_mod",),
    "_mod_scalar": ("_npi_mod_scalar",),
    "_rmod_scalar": ("_npi_rmod_scalar",),
    "power": ("_npi_power",),
    "_power_scalar": ("_npi_power_scalar",),
    "_rpower_scalar": ("_npi_rpower_scalar",),
    "maximum": ("_npi_maximum",),
    "_maximum_scalar": ("_npi_maximum_scalar",),
    "minimum": ("_npi_minimum",),
    "_minimum_scalar": ("_npi_minimum_scalar",),
    "hypot": ("_npi_hypot",),
    "_hypot_scalar": ("_npi_hypot_scalar",),
    "arccos": ("_npi_arccos",), "arccosh": ("_npi_arccosh",),
    "arcsin": ("_npi_arcsin",), "arcsinh": ("_npi_arcsinh",),
    "arctan": ("_npi_arctan",), "arctanh": ("_npi_arctanh",),
    "cos": ("_npi_cos",), "cosh": ("_npi_cosh",),
    "sin": ("_npi_sin",), "sinh": ("_npi_sinh",),
    "tan": ("_npi_tan",), "tanh": ("_npi_tanh",),
    "exp": ("_npi_exp",), "expm1": ("_npi_expm1",),
    "log": ("_npi_log",), "log10": ("_npi_log10",),
    "log1p": ("_npi_log1p",), "log2": ("_npi_log2",),
    "sqrt": ("_npi_sqrt",), "square": ("_npi_square",),
    "cbrt": ("_npi_cbrt",), "ceil": ("_npi_ceil",),
    "floor": ("_npi_floor",), "fix": ("_npi_fix",),
    "rint": ("_npi_rint",), "trunc": ("_npi_trunc",),
    "sign": ("_npi_sign",), "negative": ("_npi_negative",),
    "reciprocal": ("_npi_reciprocal",),
    "radians": ("_npi_radians", "_npi_deg2rad"),
    "degrees": ("_npi_degrees", "_npi_rad2deg"),
    "logical_not": ("_npi_logical_not",),
    "argmax": ("_npi_argmax",), "argmin": ("_npi_argmin",),
    "cast": ("_npi_cast", "_npx_cast"),
    "clip": ("_npi_clip",),
    "concat": ("_npi_concatenate",),
    "cumsum": ("_np_cumsum",),
    "gather_nd": ("_npi_gather_nd",),
    "expand_dims": ("_npi_expand_dims",),
    "flip": ("_npi_flip",),
    "_eye": ("_npi_eye",),
    "_full": ("_npi_full",),
    "_ones": ("_npi_ones",),
    "_zeros": ("_npi_zeros",),
    "_linspace": ("_npi_linspace",),
    "_arange": ("_npi_arange",),
    "_histogram": ("_npi_histogram",),
    "mean": ("_npi_mean",),
    "max": ("_np_max",), "min": ("_np_min",),
    "sum": ("_np_sum",), "prod": ("_np_prod",),
    "broadcast_to": ("_np_broadcast_to",),
    "_copy": ("_np_copy",),
    "ones_like": ("_np_ones_like",), "zeros_like": ("_np_zeros_like",),
    "squeeze": ("_np_squeeze",),
    "repeat": ("_np_repeat",),
    "roll": ("_np_roll",),
    "dot": ("_np_dot",),
    "reshape": ("_npi_reshape", "_np_reshape", "_npx_reshape"),
    "transpose": ("_np_transpose",),
    "swapaxes": ("_npi_swapaxes",),
    "take": ("_npi_take",),
    "tile": ("_npi_tile",),
    "stack": ("_npi_stack",),
    "split": ("_npi_split",),
    "slice": ("_npi_slice", "_npx_slice"),
    "_slice_assign": ("_npi_slice_assign",),
    "_slice_assign_scalar": ("_npi_slice_assign_scalar",),
    "_scatter_set_nd": ("_npi_scatter_set_nd",),
    "_shuffle": ("_np__random_shuffle",),
    "_rnn_param_concat": ("_npi_rnn_param_concat",),
    "_contrib_boolean_mask": ("_npi_boolean_mask",),
    "linalg_potrf": ("_npi_cholesky",),
    "linalg_inverse": ("_npi_inv",),
    "_random_normal": ("_npi_normal",),
    "_random_uniform": ("_npi_uniform",),
    "_random_randint": ("_npi_random_randint",),
    # npx nn aliases
    "activation": ("_npx_activation",),
    "batch_dot": ("_npx_batch_dot",),
    "flatten": ("_npx_batch_flatten",),
    "batch_norm": ("_npx_batch_norm",),
    "convolution": ("_npx_convolution",),
    "deconvolution": ("_npx_deconvolution",),
    "dropout": ("_npx_dropout",),
    "embedding": ("_npx_embedding",),
    "fully_connected": ("_npx_fully_connected",),
    "gamma": ("_npx_gamma",),
    "layer_norm": ("_npx_layer_norm",),
    "LeakyReLU": ("_npx_leaky_relu",),
    "log_softmax": ("_npx_log_softmax",),
    "one_hot": ("_npx_one_hot",),
    "pick": ("_npx_pick",),
    "pooling": ("_npx_pooling",),
    "relu": ("_npx_relu",),
    "reshape_like": ("_npx_reshape_like",),
    "ROIPooling": ("_npx_roi_pooling",),
    "sequence_mask": ("_npx_sequence_mask",),
    "sigmoid": ("_npx_sigmoid",),
    "smooth_l1": ("_npx_smooth_l1",),
    "softmax": ("_npx_softmax",),
    "topk": ("_npx_topk",),
}

for _existing, _names in _NPI_ALIASES.items():
    register_alias(_existing, *_names)
