"""INT8 quantization op family.

Role parity: reference ``src/operator/quantization/`` (quantize_v2,
dequantize, requantize, quantized_conv/fully_connected/pooling/act/
flatten/elemwise_add/concat/batch_norm, calibrate_entropy — ~6K LoC of
MKL-DNN/cuDNN int8 kernels). TPU-native: int8 storage with float32 (1,)
min/max range tensors traveling alongside, and the compute ops accumulate
``int8 x int8 -> int32`` through ``lax.dot_general`` /
``conv_general_dilated`` with ``preferred_element_type=int32`` — the exact
form XLA lowers onto the MXU's int8 systolic path on TPU.

Range convention (matches the reference's symmetric int8 mode and
``mxnet_tpu/contrib/quantization.py``): int8 scale = max(|min|,|max|)/127;
uint8 is affine over [min, max] with 255 steps. int32 accumulators carry
the product range ±(2^31-1)*s_data*s_weight.
"""
from __future__ import annotations

import numpy as _np
import jax.numpy as jnp
from jax import lax

from .registry import register, get_op

_INT32_MAX = float(2 ** 31 - 1)


def _maxabs(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


def _r1(v, dtype=jnp.float32):
    return jnp.asarray(v, dtype).reshape(1)


# ------------------------------------------------------------ (de)quantize

@register("_contrib_quantize_v2", aliases=("quantize_v2",), n_out=3,
          differentiable=False)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """float -> int8/uint8 with attached (1,) float range tensors."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    if out_type == "uint8":
        scale = jnp.maximum((mx - mn) / 255.0, 1e-12)
        q = jnp.clip(jnp.round((data - mn) / scale), 0, 255).astype(jnp.uint8)
        return q, _r1(mn), _r1(mx)
    amax = jnp.maximum(_maxabs(mn, mx), 1e-12)
    scale = amax / 127.0
    q = jnp.clip(jnp.round(data / scale), -127, 127).astype(jnp.int8)
    return q, _r1(-amax), _r1(amax)


@register("_contrib_quantize", aliases=("quantize",), n_out=3,
          differentiable=False)
def quantize(data, min_range, max_range, out_type="uint8"):
    """Like quantize_v2 but takes the range as (1,) tensors (reference
    quantize.cc signature)."""
    mn = jnp.asarray(min_range).reshape(()).astype(jnp.float32)
    mx = jnp.asarray(max_range).reshape(()).astype(jnp.float32)
    if out_type == "uint8":
        scale = jnp.maximum((mx - mn) / 255.0, 1e-12)
        q = jnp.clip(jnp.round((data - mn) / scale), 0, 255).astype(jnp.uint8)
        return q, _r1(mn), _r1(mx)
    amax = jnp.maximum(_maxabs(mn, mx), 1e-12)
    q = jnp.clip(jnp.round(data / (amax / 127.0)), -127, 127).astype(jnp.int8)
    return q, _r1(-amax), _r1(amax)


@register("_contrib_dequantize", aliases=("dequantize",),
          differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    mn = jnp.asarray(min_range).reshape(()).astype(jnp.float32)
    mx = jnp.asarray(max_range).reshape(()).astype(jnp.float32)
    if data.dtype == jnp.uint8:
        scale = jnp.maximum((mx - mn) / 255.0, 1e-12)
        return data.astype(jnp.float32) * scale + mn
    if data.dtype == jnp.int32:
        scale = _maxabs(mn, mx) / _INT32_MAX
    else:
        scale = _maxabs(mn, mx) / 127.0
    return data.astype(jnp.float32) * scale


@register("_contrib_requantize", aliases=("requantize",), n_out=3,
          differentiable=False)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8, optionally narrowing to a calibrated range."""
    real = dequantize.fn(data, min_range, max_range)
    if min_calib_range is not None and max_calib_range is not None:
        amax = max(abs(float(min_calib_range)), abs(float(max_calib_range)))
        amax = jnp.float32(amax)
    else:
        amax = jnp.maximum(jnp.max(jnp.abs(real)), 1e-12)
    q = jnp.clip(jnp.round(real / (amax / 127.0)), -127, 127).astype(jnp.int8)
    return q, _r1(-amax), _r1(amax)


# --------------------------------------------------------- int8 compute ops

def _i32_ranges(min_d, max_d, min_w, max_w):
    s = (_maxabs(jnp.asarray(min_d).reshape(()),
                 jnp.asarray(max_d).reshape(())) / 127.0) * \
        (_maxabs(jnp.asarray(min_w).reshape(()),
                 jnp.asarray(max_w).reshape(())) / 127.0)
    amax = s * _INT32_MAX
    return s, _r1(-amax), _r1(amax)


def _bias_to_i32(bias, min_b, max_b, s_out):
    sb = _maxabs(jnp.asarray(min_b).reshape(()),
                 jnp.asarray(max_b).reshape(())) / 127.0
    return jnp.round(bias.astype(jnp.float32) * (sb / s_out)).astype(jnp.int32)


@register("_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",), n_out=3,
          differentiable=False)
def quantized_fully_connected(data, weight, bias=None, min_data=0.0,
                              max_data=0.0, min_weight=0.0, max_weight=0.0,
                              min_bias=0.0, max_bias=0.0, num_hidden=0,
                              no_bias=False, flatten=True):
    """int8 x int8 -> int32 FC on the MXU int8 path."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    acc = lax.dot_general(x, weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    s_out, mn, mx = _i32_ranges(min_data, max_data, min_weight, max_weight)
    if bias is not None and not no_bias:
        acc = acc + _bias_to_i32(bias, min_bias, max_bias, s_out)
    return acc, mn, mx


@register("_contrib_quantized_conv", aliases=("quantized_conv",), n_out=3,
          differentiable=False)
def quantized_conv(data, weight, bias=None, min_data=0.0, max_data=0.0,
                   min_weight=0.0, max_weight=0.0, min_bias=0.0,
                   max_bias=0.0, kernel=(), stride=(), pad=(), dilate=(),
                   num_filter=0, no_bias=False, layout="NCHW"):
    """int8 conv accumulating int32 (NCHW activations, OIHW weights)."""
    nd = data.ndim - 2
    stride = tuple(stride) or (1,) * nd
    pad = tuple(pad) or (0,) * nd
    dilate = tuple(dilate) or (1,) * nd
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if nd == 2 else ("NCW", "OIW", "NCW"))
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8), stride,
        [(p, p) for p in pad], rhs_dilation=dilate, dimension_numbers=dn,
        preferred_element_type=jnp.int32)
    s_out, mn, mx = _i32_ranges(min_data, max_data, min_weight, max_weight)
    if bias is not None and not no_bias:
        b = _bias_to_i32(bias, min_bias, max_bias, s_out)
        acc = acc + b.reshape((1, -1) + (1,) * nd)
    return acc, mn, mx


@register("_contrib_quantized_pooling", aliases=("quantized_pooling",),
          n_out=3, differentiable=False)
def quantized_pooling(data, min_data=0.0, max_data=0.0, kernel=(),
                      pool_type="max", stride=(), pad=(),
                      global_pool=False, **kwargs):
    """Pooling directly on the int8 payload — ranges pass through unchanged
    (max) or stay valid bounds (avg)."""
    pool = get_op("Pooling")
    if pool_type == "avg":
        out = pool.fn(data.astype(jnp.int32), kernel=kernel,
                      pool_type="avg", stride=stride, pad=pad,
                      global_pool=global_pool)
        lo, hi = ((0, 255) if data.dtype == jnp.uint8 else (-127, 127))
        out = jnp.clip(jnp.round(out), lo, hi).astype(data.dtype)
    else:
        # the generic Pooling kernel's -inf init value has no int8 analogue;
        # widen to int32 for the reduce-window, payload is exact either way
        out = pool.fn(data.astype(jnp.int32), kernel=kernel,
                      pool_type="max", stride=stride, pad=pad,
                      global_pool=global_pool).astype(data.dtype)
    return (out, _r1(jnp.asarray(min_data).reshape(())),
            _r1(jnp.asarray(max_data).reshape(())))


@register("_contrib_quantized_act", aliases=("quantized_act",), n_out=3,
          differentiable=False)
def quantized_act(data, min_data=0.0, max_data=0.0, act_type="relu"):
    if act_type != "relu":
        raise NotImplementedError(
            "quantized_act supports relu only (reference mkldnn parity)")
    out = jnp.maximum(data, jnp.zeros((), data.dtype))
    return (out, _r1(jnp.asarray(min_data).reshape(())),
            _r1(jnp.asarray(max_data).reshape(())))


@register("_contrib_quantized_flatten", aliases=("quantized_flatten",),
          n_out=3, differentiable=False)
def quantized_flatten(data, min_data=0.0, max_data=0.0):
    out = data.reshape(data.shape[0], -1)
    return (out, _r1(jnp.asarray(min_data).reshape(())),
            _r1(jnp.asarray(max_data).reshape(())))


@register("_contrib_quantized_elemwise_add",
          aliases=("quantized_elemwise_add",), n_out=3,
          differentiable=False)
def quantized_elemwise_add(lhs, rhs, lhs_min=0.0, lhs_max=0.0,
                           rhs_min=0.0, rhs_max=0.0):
    """int8 + int8 -> int32 at a shared scale: both sides are rescaled into
    the wider of the two ranges before adding."""
    sl = _maxabs(jnp.asarray(lhs_min).reshape(()),
                 jnp.asarray(lhs_max).reshape(())) / 127.0
    sr = _maxabs(jnp.asarray(rhs_min).reshape(()),
                 jnp.asarray(rhs_max).reshape(())) / 127.0
    # int32 payload at scale s_out/2^22 keeps 8 guard bits against overflow
    s_out = jnp.maximum(sl, sr) / (1 << 22)
    acc = (jnp.round(lhs.astype(jnp.float32) * (sl / s_out)).astype(jnp.int32)
           + jnp.round(rhs.astype(jnp.float32) * (sr / s_out)).astype(
               jnp.int32))
    amax = s_out * _INT32_MAX
    return acc, _r1(-amax), _r1(amax)


@register("_contrib_quantized_concat", aliases=("quantized_concat",),
          n_out=0, differentiable=False)
def quantized_concat(*args, num_args=0, dim=1):
    """Concat int8 inputs after rescaling every payload to the widest range.

    Call layout mirrors the reference: ``num_args`` data tensors followed by
    their (min, max) pairs interleaved per input.
    """
    n = int(num_args) or len(args) // 3
    data, mins, maxs = args[:n], args[n::2][:n], args[n + 1::2][:n]
    scales = [_maxabs(jnp.asarray(mn).reshape(()),
                      jnp.asarray(mx).reshape(())) / 127.0
              for mn, mx in zip(mins, maxs)]
    s_out = scales[0]
    for s in scales[1:]:
        s_out = jnp.maximum(s_out, s)
    parts = [jnp.clip(jnp.round(d.astype(jnp.float32) * (s / s_out)),
                      -127, 127).astype(jnp.int8)
             for d, s in zip(data, scales)]
    amax = s_out * 127.0
    return jnp.concatenate(parts, axis=dim), _r1(-amax), _r1(amax)


@register("_contrib_quantized_batch_norm", aliases=("quantized_batch_norm",),
          n_out=3, differentiable=False)
def quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                         min_data=0.0, max_data=0.0, eps=1e-3,
                         min_calib_range=None, max_calib_range=None,
                         **kwargs):
    """Inference BN folded to per-channel scale/shift in float, re-quantized
    to int8 (reference mkldnn_quantized_batch_norm)."""
    s_in = _maxabs(jnp.asarray(min_data).reshape(()),
                   jnp.asarray(max_data).reshape(())) / 127.0
    x = data.astype(jnp.float32) * s_in
    inv = gamma / jnp.sqrt(moving_var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    y = (x - moving_mean.reshape(shape)) * inv.reshape(shape) + \
        beta.reshape(shape)
    if min_calib_range is not None and max_calib_range is not None:
        amax = jnp.float32(max(abs(float(min_calib_range)),
                               abs(float(max_calib_range))))
    else:
        amax = jnp.maximum(jnp.max(jnp.abs(y)), 1e-12)
    q = jnp.clip(jnp.round(y / (amax / 127.0)), -127, 127).astype(jnp.int8)
    return q, _r1(-amax), _r1(amax)


@register("_contrib_calibrate_entropy", aliases=("calibrate_entropy",),
          n_out=2, differentiable=False)
def calibrate_entropy(hist, hist_edges, num_quantized_bins=255,
                      search_stride=1):
    """KL-divergence threshold search over an activation histogram
    (reference calibrate.cc / the python _LayerOutputCollector path).
    Host-side numpy: calibration is offline, never inside a jitted step.

    ``search_stride``: evaluate every stride-th candidate threshold. The
    reference scans every candidate (stride 1, the default here); larger
    strides trade calibration time for threshold granularity (round-2
    advisor finding: the old fixed stride of 8 was an undocumented
    deviation)."""
    hist = _np.asarray(hist, dtype=_np.float64)
    edges = _np.asarray(hist_edges, dtype=_np.float64)
    num_bins = hist.size
    centers = (edges[:-1] + edges[1:]) / 2.0
    best_t, best_kl = float(edges[-1]), _np.inf
    start = num_quantized_bins // 2
    for i in range(start, num_bins + 1, max(1, int(search_stride))):
        t = centers[min(i, num_bins - 1)]
        p = hist[:i].copy()
        outliers = hist[i:].sum()
        if p.size == 0 or p.sum() + outliers == 0:
            continue
        p[-1] += outliers
        # quantize p into num_quantized_bins then expand back
        factor = max(1, p.size // num_quantized_bins)
        q = _np.zeros_like(p)
        for j in range(0, p.size, factor):
            chunk = p[j:j + factor]
            nz = (chunk > 0).sum()
            if nz:
                q[j:j + factor] = _np.where(chunk > 0, chunk.sum() / nz, 0)
        pm, qm = p / max(p.sum(), 1e-12), q / max(q.sum(), 1e-12)
        mask = (pm > 0) & (qm > 0)
        kl = float((pm[mask] * _np.log(pm[mask] / qm[mask])).sum())
        if kl < best_kl:
            best_kl, best_t = kl, float(abs(t))
    return (jnp.asarray([-best_t], jnp.float32),
            jnp.asarray([best_t], jnp.float32))
