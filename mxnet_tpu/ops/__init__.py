"""Operator registry package.

Importing this package registers the full op corpus (core + nn). Namespaces
(mx.nd, mx.sym, mx.np) are *generated* from the registry at import, the same
mechanism as the reference's generated op modules
(reference `python/mxnet/ndarray/register.py:116`
_generate_ndarray_function_code)."""
from . import registry
from .registry import register, get_op, list_ops, invoke, Op
from . import core      # noqa: F401  (registers core tensor ops)
from . import nn        # noqa: F401  (registers NN ops)
from . import contrib_ops  # noqa: F401
from . import ctc       # noqa: F401  (CTC loss dynamic program)
from . import rnn       # noqa: F401  (fused RNN scan layers)
from . import tensor_extra  # noqa: F401  (scalar/creation/indexing breadth)
from . import ste_graph_ops  # noqa: F401  (STEs, grad multiplier, DGL names)
from . import optim_ops  # noqa: F401  (optimizer update kernels)
from . import random_ops  # noqa: F401  (sampling ops)
from . import linalg_extra  # noqa: F401
from . import loss_ops  # noqa: F401  (regression outputs, ROI)
from . import image_ops  # noqa: F401
from . import detection_ops  # noqa: F401  (contrib detection family)
from . import transformer_ops  # noqa: F401  (interleaved attention matmuls)
from . import quantized_ops  # noqa: F401  (INT8 quantization op family)
from . import spatial_ops  # noqa: F401  (grid/sampler/STN, SVM, FFT, corr)
from . import proposal_ops  # noqa: F401  (RPN/SSD/deformable family)
from . import contrib_misc  # noqa: F401  (quadratic/index/hawkes etc)
from . import generation_ops  # noqa: F401  (seeded sampling, KV-cache writes)
from . import numpy_ops  # noqa: F401  (_npi_/_np_/_npx_ registrations;
#                                       aliases ops above, keep last)

# remaining reference registration names that are pure aliases here:
# CTCLoss (reference ctc_loss.cc registers both), *_v1 legacy conv/pool
# (reference convolution_v1.cc — same math, older layout constraints), and
# the control-flow trio (reference control_flow.cc:1089-1255) whose
# callable-subgraph arguments pass through invoke untouched.
registry.register_alias("_ctc_loss", "CTCLoss")
registry.register_alias("Convolution", "Convolution_v1")
registry.register_alias("Pooling", "Pooling_v1")
register("_foreach", n_out=0)(contrib_ops.foreach)
register("_while_loop", n_out=0)(contrib_ops.while_loop)
register("_cond", n_out=0)(contrib_ops.cond)

# the `_sample_*` ops are public `mx.nd.sample_*` in the reference
# (tests/python/unittest/test_operator.py:9320 mx.nd.sample_normal)
for _s in ("normal", "uniform", "exponential", "gamma", "poisson",
           "negative_binomial", "generalized_negative_binomial",
           "unique_zipfian"):
    if get_op("sample_" + _s) is None and get_op("_sample_" + _s):
        registry.register_alias("_sample_" + _s, "sample_" + _s)


def populate_namespace(target, names=None):
    """Inject registered ops into a module/dict namespace (mx.nd codegen)."""
    for name in (names or list_ops()):
        op = get_op(name)
        if op is not None:
            target[name] = op

# Legacy v0 capitalized binary-op names (reference
# elemwise_binary_op_basic.cc:94 .add_alias("_Plus") etc.), npx-namespace
# detection/rnn exposures, and contrib spellings — registered here after
# every op module has loaded.
for _src, _names in [
        ("_plus", ("_Plus",)), ("_minus", ("_Minus",)),
        ("_mul", ("_Mul",)), ("_div", ("_Div",)),
        ("_power", ("_Power",)),
        ("_maximum", ("_Maximum",)), ("_minimum", ("_Minimum",)),
        ("_equal", ("_Equal",)), ("_not_equal", ("_Not_Equal",)),
        ("_greater", ("_Greater",)),
        ("_greater_equal", ("_Greater_Equal",)),
        ("_lesser", ("_Lesser",)), ("_lesser_equal", ("_Lesser_Equal",)),
        ("ctc_loss", ("_contrib_CTCLoss",)),
        ("_contrib_box_nms", ("_contrib_box_non_maximum_suppression",)),
        ("_contrib_MultiBoxDetection", ("_npx_multibox_detection",)),
        ("_contrib_MultiBoxPrior", ("_npx_multibox_prior",)),
        ("_contrib_MultiBoxTarget", ("_npx_multibox_target",)),
        ("RNN", ("_npx_rnn",))]:
    registry.register_alias(_src, *_names)
del _src, _names
