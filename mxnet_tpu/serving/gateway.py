"""Horizontal serving: a fault-tolerant, load-aware gateway over N replicas.

Role parity: the front-end/replica split every production serving system
lands on — TF-Serving behind its router, Clipper's query frontend over
model containers (both already cited in ``serving/engine.py``). Every
layer below this one (engine, generation, fleet, AOT restart) scales one
process; this module makes replica loss a reroute instead of an outage:

- **Least-loaded routing** — a background scraper fans out (in parallel,
  ``tools/telemetry_agg.py``-style) to every replica's ``/healthz`` +
  ``/metrics`` and keeps a live load view: batcher queue depth (the
  ``serving.queue_depth`` gauge), breaker state, degraded health, HBM
  headroom. Requests go to the lowest-scoring routable replica, with the
  gateway's own in-flight count as the between-scrapes signal.
- **Failover** — connect failures and 5xx replies re-route to the
  next-best replica under the existing
  :class:`~mxnet_tpu.resilience.retry.RetryPolicy`
  (:class:`ReplicaUnavailable` is a ``TransientFault``, so the stock
  policy absorbs it); ``/predict`` is idempotent, so a replica that dies
  mid-request costs a retry, not a client-visible error.
- **Ejection** — every replica gets a gateway-side
  :class:`~mxnet_tpu.resilience.breaker.CircuitBreaker`; a flapping
  backend is ejected from routing and earns readmission through the
  breaker's half-open probe.
- **Sticky streams** — a ``/generate`` stream pins its replica for the
  whole response (continuous batching holds the KV slot there); replica
  death mid-stream surfaces the protocol's existing in-band ``error``
  line and frees the pin.
- **Drain-aware rolling restart** — :meth:`Gateway.rolling_restart`
  cycles the fleet one replica at a time: stop routing → ``GET /drain``
  on the replica → wait for in-flight + pins to clear → backend restart
  (onto the AOT zero-compile path when artifacts are published) →
  health-gated readmission. Zero dropped requests.
- **SLO-driven autoscale** — :class:`Autoscaler` grows the replica set on
  sustained queue-depth / p99-SLO burn and shrinks it through the same
  drain machinery, never below the floor.

Topology: clients → ``Gateway`` (this module, stdlib HTTP) → N
``ModelServer`` replicas (separate processes in production —
``tools/serve_fleet.py`` spawns and supervises them — or in-process
servers in tests). ``X-Request-Id`` is honored/minted at the gateway and
forwarded, so one id names the request across gateway spans
(``gateway.route`` / ``gateway.failover``) and the replica's own
``serving.http`` span chain; ``X-Model-Version`` from fleet replicas is
echoed back unchanged.
"""
from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse
import urllib.request
import uuid
from collections import deque
from http.server import BaseHTTPRequestHandler

from .. import config as _config
from ..observability import tracer as _trace
from ..resilience import chaos as _chaos
from ..resilience import retry as _retry
from ..resilience.breaker import CircuitBreaker
from .metrics import _percentiles

__all__ = ["Gateway", "Autoscaler", "Replica", "GatewayMetrics",
           "ReplicaUnavailable", "NoRoutableReplica",
           "GATEWAY_PROM_COUNTERS", "GATEWAY_PROM_GAUGES"]

# replica lifecycle (breaker-open "ejected" is derived, not a state:
# the breaker owns its own recovery clock)
JOINING, UP, DRAINING = "joining", "up", "draining"


class ReplicaUnavailable(_chaos.TransientFault):
    """One forward attempt failed for replica-side reasons (connect
    error, mid-read death, 5xx). Subclasses ``TransientFault`` so the
    stock env-configured :class:`RetryPolicy` re-routes it — failover IS
    a retry, with the next attempt picking the next-best replica."""


class NoRoutableReplica(RuntimeError):
    """Every replica is down/draining/ejected (mapped to HTTP 503)."""


# Prometheus exposition descriptors (rendered by
# observability/export_prom.py) — kept next to the counters they
# describe, like serving/metrics.py does.
GATEWAY_PROM_COUNTERS = (
    ("requests", "routed /predict requests (ok + errors)"),
    ("ok", "routed requests that returned a replica's 2xx/4xx reply"),
    ("errors", "client-visible gateway failures (all replicas exhausted)"),
    ("failovers", "re-routes to another replica after a forward failure"),
    ("no_replica", "requests that found zero routable replicas"),
    ("streams", "routed /generate streams"),
    ("stream_errors", "streams that lost their replica mid-flight"),
    ("ejections", "replica breaker trips (backend ejected from routing)"),
    ("readmissions", "replicas readmitted via half-open probe success"),
    ("drains", "replica drains started (restart/scale-down)"),
    ("rolling_restarts", "full-fleet rolling restarts completed"),
    ("scale_ups", "autoscaler replica additions"),
    ("scale_downs", "autoscaler replica removals"),
)
GATEWAY_PROM_GAUGES = (
    ("qps", "routed requests/s over the sliding window"),
    ("replicas", "replicas known to the gateway"),
    ("ready_replicas", "replicas currently routable"),
    ("draining_replicas", "replicas draining for restart/removal"),
)


class Replica:
    """One backend in the gateway's routing table. Load fields are
    written by the scraper thread and the request path under the
    gateway's lock; ``meta`` is the backend handle (a process record for
    ``tools/serve_fleet.py``, a server object in tests)."""

    __slots__ = ("id", "url", "state", "health", "breaker", "queue_depth",
                 "headroom", "inflight", "pins", "routed", "failures",
                 "scrape_failures", "generation", "mesh", "meta")

    def __init__(self, rid, url, breaker, meta=None):
        self.id = rid
        self.url = url.rstrip("/")
        self.state = JOINING
        self.health = "unknown"   # ok | degraded | draining | down
        self.breaker = breaker
        self.queue_depth = 0
        self.headroom = None
        self.inflight = 0
        self.pins = 0
        self.routed = 0
        self.failures = 0
        self.scrape_failures = 0
        self.generation = 0       # bumped per restart
        self.mesh = None          # sharded lane: /metrics "mesh" gauge
        self.meta = meta

    @property
    def chips(self):
        """Devices behind this replica: a sharded replica is a planned
        mesh of M chips, not one — the autoscaler's capacity unit."""
        if isinstance(self.mesh, dict):
            try:
                return max(1, int(self.mesh.get("n_devices") or 1))
            except (TypeError, ValueError):
                return 1
        return 1

    def describe(self):
        return {
            "id": self.id, "url": self.url, "state": self.state,
            "health": self.health, "queue_depth": self.queue_depth,
            "headroom": self.headroom, "inflight": self.inflight,
            "pins": self.pins, "routed": self.routed,
            "failures": self.failures, "generation": self.generation,
            "mesh": self.mesh, "chips": self.chips,
            "breaker": self.breaker.snapshot()["state"],
        }


class GatewayMetrics:
    """Gateway-side counters + latency window, exported like
    :class:`~.metrics.ServingMetrics`: :meth:`snapshot` (``/metrics``),
    ``gateway.*`` profiler rows, and the ``mxtpu_gateway_*`` OpenMetrics
    families."""

    def __init__(self, window=2048, name="gateway"):
        self.name = name
        self._lock = threading.Lock()
        self._window = deque(maxlen=window)  # (done_t, latency_s)
        self._c = {k: 0 for k, _ in GATEWAY_PROM_COUNTERS}
        self._latency_total = 0.0
        self._t0 = time.time()
        self._replica_table_fn = None
        self._bound_provider = None

    def count(self, key, n=1):
        with self._lock:
            self._c[key] += n

    def record_request(self, latency_s, ok=True):
        with self._lock:
            self._c["requests"] += 1
            self._c["ok" if ok else "errors"] += 1
            self._latency_total += latency_s
            self._window.append((time.time(), latency_s))

    def p99_ms(self):
        """Gateway-observed p99 over the sliding window — the
        autoscaler's latency-SLO signal."""
        with self._lock:
            lats = [l for _, l in self._window]
        return _percentiles(lats, qs=(99,))["p99"]

    def set_replica_table_fn(self, fn):
        self._replica_table_fn = fn

    def snapshot(self):
        with self._lock:
            c = dict(self._c)
            window = list(self._window)
            latency_total = self._latency_total
        if len(window) >= 2:
            span = max(window[-1][0] - window[0][0], 1e-9)
            qps = (len(window) - 1) / span
        elif c["requests"]:
            qps = c["requests"] / max(time.time() - self._t0, 1e-9)
        else:
            qps = 0.0
        lat = _percentiles([l for _, l in window])
        lat["mean"] = (latency_total / c["requests"] * 1e3
                       if c["requests"] else 0.0)
        out = {"name": self.name, "qps": qps, "latency_ms": lat,
               "uptime_s": time.time() - self._t0}
        out.update(c)
        if self._replica_table_fn is not None:
            try:
                table = self._replica_table_fn()
            except Exception:
                table = {}
            out["replica_table"] = table
            states = [r["state"] for r in table.values()]
            healths = [(r["state"], r["health"], r["breaker"])
                       for r in table.values()]
            out["replicas"] = len(table)
            out["ready_replicas"] = sum(
                1 for s, h, b in healths
                if s == UP and h == "ok" and b != "open")
            out["draining_replicas"] = states.count(DRAINING)
        return out

    def profiler_rows(self):
        with self._lock:
            c = dict(self._c)
            latency_total = self._latency_total
        rows = {"gateway.requests": (c["requests"], latency_total)}
        for key in ("failovers", "no_replica", "ejections", "readmissions",
                    "streams", "stream_errors", "drains", "scale_ups",
                    "scale_downs", "rolling_restarts"):
            rows["gateway." + key] = (c[key], 0.0)
        return rows

    def bind_profiler(self):
        from .. import profiler as _profiler
        if self._bound_provider is None:
            self._bound_provider = self.profiler_rows
            _profiler.register_stats_provider(self._bound_provider)
        return self

    def unbind_profiler(self):
        from .. import profiler as _profiler
        if self._bound_provider is not None:
            _profiler.unregister_stats_provider(self._bound_provider)
            self._bound_provider = None


# ---------------------------------------------------------------------------
# HTTP front
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet_tpu_gateway/0.1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _reply(self, code, payload, headers=None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code, body, content_type):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _reply_raw(self, code, body, headers):
        """Relay a replica's buffered reply verbatim (status + body +
        the attribution headers that must survive the hop)."""
        self.send_response(code)
        for k, v in headers.items():
            self.send_header(k, v)
        if "Content-Type" not in headers:
            self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None and "X-Request-Id" not in headers:
            self.send_header("X-Request-Id", rid)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        self._request_id = None
        gw = self.server.gateway
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._reply(200, gw.health())
        elif path == "/metrics.prom" or (
                path == "/metrics" and "format=prometheus" in query):
            from ..observability import export_prom as _prom
            self._reply_text(200, _prom.render_gateway(gw),
                             _prom.CONTENT_TYPE)
        elif path == "/metrics":
            self._reply(200, gw.metrics.snapshot())
        elif path == "/replicas":
            self._reply(200, {"replicas": gw.replica_table(),
                              "events": gw.events()})
        else:
            self._reply(404, {"error": "unknown path %s" % self.path})

    def _read_body(self):
        from .server import read_post_body
        return read_post_body(self)

    def do_POST(self):  # noqa: N802
        gw = self.server.gateway
        rid = self.headers.get("X-Request-Id") or uuid.uuid4().hex[:16]
        self._request_id = rid
        body = self._read_body()
        if body is None:
            return
        path, _, query = self.path.partition("?")
        if path == "/predict" or path.startswith("/predict/"):
            self._route_predict(gw, path, body, rid)
        elif path == "/generate" or path.startswith("/generate/"):
            self._route_generate(gw, path, body, rid)
        elif path == "/debug/profile":
            self._route_profile(gw, query, body, rid)
        else:
            self._reply(404, {"error": "unknown path %s" % self.path})

    def _route_profile(self, gw, query, body, rid):
        """``POST /debug/profile?replica=ID&seconds=N``: proxy an
        on-demand profile capture to ONE named replica (capturing "the
        fleet" is meaningless — traces are per-process). The caller must
        present the SAME admin token here that a replica would demand
        (the gateway re-attaches it on the replica hop) — proxying
        without the check would turn the gateway into a confused deputy
        that launders unauthenticated capture requests through its own
        credential. The forward timeout is stretched past the capture
        window: a 30s capture is not a dead replica."""
        if gw._admin_token and \
                self.headers.get("X-Admin-Token") != gw._admin_token:
            self._reply(403, {"error": "admin endpoint: missing or bad "
                                       "X-Admin-Token"})
            return
        params = urllib.parse.parse_qs(query)
        rep_id = params.get("replica", [None])[0]
        if rep_id is None:
            self._reply(400, {"error": "need ?replica=<id> (see "
                                       "/replicas for ids)"})
            return
        try:
            rep = gw.replica(int(rep_id))
        except ValueError:
            rep = None
        if rep is None:
            self._reply(404, {"error": "unknown replica %r" % rep_id})
            return
        try:
            seconds = float(params.get("seconds", ["1"])[0])
        except ValueError:
            self._reply(400, {"error": "bad seconds value"})
            return
        status, headers, data = gw.forward_profile(rep, seconds, body,
                                                   rid)
        self._reply_raw(status, data, headers)

    def _route_predict(self, gw, path, body, rid):
        t0 = time.monotonic()
        try:
            status, headers, data = gw.forward_predict(path, body, rid)
        except NoRoutableReplica as e:
            gw.metrics.record_request(time.monotonic() - t0, ok=False)
            self._reply(503, {"error": str(e)},
                        headers={"Retry-After": "1"})
            return
        except _retry.RetryExhausted as e:
            gw.metrics.record_request(time.monotonic() - t0, ok=False)
            self._reply(503, {"error": "all replicas failed: %s" % e},
                        headers={"Retry-After": "1"})
            return
        except _chaos.TransientFault as e:
            # retry_policy=False (single attempt): ReplicaUnavailable /
            # an armed gateway.forward fault has no RetryPolicy to wrap
            # it — still a typed 503, never a dropped connection
            gw.metrics.record_request(time.monotonic() - t0, ok=False)
            self._reply(503, {"error": str(e)},
                        headers={"Retry-After": "1"})
            return
        gw.metrics.record_request(time.monotonic() - t0, ok=True)
        self._reply_raw(status, data, headers)

    def _route_generate(self, gw, path, body, rid):
        gw.stream_generate(self, path, body, rid)


# ---------------------------------------------------------------------------
# the gateway
# ---------------------------------------------------------------------------

class Gateway:
    """Load-aware HTTP router over N ``ModelServer`` replicas.

    Parameters
    ----------
    replicas : iterable of str, optional
        Initial replica base URLs (``http://host:port``). Each starts
        ``joining`` and is promoted to ``up`` by its first healthy
        scrape (health-gated admission — a replica still compiling its
        ladder takes no traffic until ``/healthz`` says ``ok``).
    backend : object, optional
        Replica lifecycle provider for rolling restarts and autoscaling.
        Duck-typed: ``spawn() -> (url, meta)``, ``restart(replica) ->
        new_url | None``, ``stop(replica)``. ``tools/serve_fleet.py``
        ships the subprocess implementation; tests wrap in-process
        servers.
    scrape_ms : float, optional
        Load-scrape interval (default ``MXNET_GATEWAY_SCRAPE_MS``);
        ``0`` disables the background scraper (tests drive
        :meth:`scrape_once` by hand).
    forward_timeout_s : float
        Socket timeout for forwarded requests (covers the replica's own
        queue deadline; scrapes use the much shorter
        ``MXNET_GATEWAY_CONNECT_TIMEOUT_MS``).
    retry_policy : RetryPolicy, optional
        Failover policy. Default builds the env-configured
        ``retry.gateway`` named policy (``MXNET_RETRY_*``); each retry
        attempt re-picks the next-best untried replica. ``False``
        disables failover (single attempt).
    admin_token : str, optional
        Sent as ``X-Admin-Token`` on replica ``/drain`` calls (default
        ``MXNET_SERVING_ADMIN_TOKEN``).
    event_log : str or callable, optional
        Path for JSON-lines lifecycle transitions (replica up/drain/
        restart/eject/scale), or a callable receiving each event dict.
        The last 256 events are always kept in memory (:meth:`events`).
    """

    def __init__(self, replicas=(), backend=None, host="127.0.0.1",
                 port=0, scrape_ms=None, forward_timeout_s=30.0,
                 retry_policy=None, metrics=None, admin_token=None,
                 event_log=None, eject_failures=None,
                 eject_recovery_ms=None, bind_profiler=True,
                 clock=time.monotonic):
        self.metrics = metrics or GatewayMetrics()
        self.metrics.set_replica_table_fn(self.replica_table)
        if bind_profiler:
            self.metrics.bind_profiler()
        self._clock = clock
        self._lock = threading.RLock()
        self._replicas = {}
        self._next_id = 0
        self._backend = backend
        self._forward_timeout_s = float(forward_timeout_s)
        self._connect_timeout_s = \
            _config.get("MXNET_GATEWAY_CONNECT_TIMEOUT_MS") / 1e3
        self._scrape_s = (_config.get("MXNET_GATEWAY_SCRAPE_MS")
                          if scrape_ms is None else float(scrape_ms)) / 1e3
        self._eject_failures = (
            _config.get("MXNET_GATEWAY_EJECT_FAILURES")
            if eject_failures is None else int(eject_failures))
        self._eject_recovery_ms = (
            _config.get("MXNET_GATEWAY_EJECT_RECOVERY_MS")
            if eject_recovery_ms is None else float(eject_recovery_ms))
        if retry_policy is None:
            retry_policy = _retry.named_policy("retry.gateway")
        self._retry = retry_policy or None
        self._admin_token = (_config.get("MXNET_SERVING_ADMIN_TOKEN")
                             if admin_token is None else admin_token)
        self._events = deque(maxlen=256)
        self._event_sink = None
        self._event_path = None
        if callable(event_log):
            self._event_sink = event_log
        elif event_log:
            self._event_path = event_log
        self._event_lock = threading.Lock()
        self._closing = False
        self._scrape_thread = None
        self._scrape_wake = threading.Event()
        for url in replicas:
            self.add_replica(url)
        from .server import _QuietThreadingHTTPServer
        self._httpd = _QuietThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.gateway = self
        self._thread = None

    # ---- replica set ------------------------------------------------------
    def _mk_breaker(self, rid):
        # <=0 disables ejection (per the knob contract): the breaker
        # still exists so the outcome plumbing is uniform, but its
        # threshold is unreachably high and it never opens
        threshold = (self._eject_failures if self._eject_failures > 0
                     else (1 << 30))
        return CircuitBreaker(
            failure_threshold=threshold,
            recovery_ms=self._eject_recovery_ms,
            half_open_probes=1, clock=self._clock,
            name="gateway.replica.%d" % rid,
            register=self._eject_failures > 0)

    def add_replica(self, url, meta=None, state=JOINING):
        """Register a replica (health-gated: it takes traffic once a
        scrape sees ``/healthz`` ok). Returns the :class:`Replica`."""
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            rep = Replica(rid, url, self._mk_breaker(rid), meta=meta)
            rep.state = state
            self._replicas[rid] = rep
        self._event("replica_added", replica=rid, url=rep.url)
        return rep

    def remove_replica(self, rid):
        with self._lock:
            rep = self._replicas.pop(rid, None)
        if rep is not None:
            rep.breaker.deregister()
            self._event("replica_removed", replica=rid, url=rep.url)
        return rep

    def replica(self, rid):
        with self._lock:
            return self._replicas.get(rid)

    def replicas(self):
        with self._lock:
            return list(self._replicas.values())

    def replica_table(self):
        with self._lock:
            return {str(r.id): r.describe()
                    for r in self._replicas.values()}

    def ready_replicas(self):
        """Replicas currently eligible for new requests."""
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state == UP and r.health == "ok"
                    and r.breaker.state != "open"]

    def events(self):
        with self._event_lock:
            return list(self._events)

    def log_event(self, kind, **kw):
        """Public event hook: supervisors (``tools/serve_fleet.py``)
        record their own lifecycle transitions (spawn, crash, respawn)
        into the same JSON event stream the gateway writes."""
        self._event(kind, **kw)

    def _event(self, kind, **kw):
        evt = {"t": time.time(), "event": kind}
        evt.update(kw)
        with self._event_lock:
            self._events.append(evt)
            if self._event_path is not None:
                try:
                    with open(self._event_path, "a") as f:
                        f.write(json.dumps(evt) + "\n")
                except OSError:
                    pass
        if self._event_sink is not None:
            try:
                self._event_sink(evt)
            except Exception:
                pass
        _trace.instant("gateway.event", kind=kind,
                       replica=kw.get("replica"))

    # ---- load / health scraping -------------------------------------------
    def _fan_out(self, items, fn):
        """Run ``fn(item)`` concurrently, one thread per item, bounded by
        the scrape timeout — the ``tools/telemetry_agg.py`` pattern: a
        dead replica costs ONE timeout, not one per replica, so losing
        hosts can't make the load signal go stale for the healthy ones."""
        results = {}
        threads = []
        for key, item in items:
            def _run(key=key, item=item):
                results[key] = fn(item)
            t = threading.Thread(target=_run, daemon=True,
                                 name="gateway-scrape-%s" % key)
            t.start()
            threads.append(t)
        # a scrape is TWO sequential requests (/healthz then /metrics),
        # each bounded by the connect timeout — the join deadline must
        # cover both or a slow-but-alive replica gets marked down
        deadline = time.monotonic() + 2.0 * self._connect_timeout_s + 1.0
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        return results

    def _scrape_replica(self, url):
        """One replica's (health_status, queue_depth, headroom, mesh)
        or None when unreachable."""
        try:
            with urllib.request.urlopen(
                    url + "/healthz",
                    timeout=self._connect_timeout_s) as r:
                health = json.loads(r.read()).get("status", "ok")
        except Exception:
            return None
        queue_depth, headroom, mesh = 0, None, None
        try:
            with urllib.request.urlopen(
                    url + "/metrics",
                    timeout=self._connect_timeout_s) as r:
                snap = json.loads(r.read())
            qd = snap.get("queue_depth")
            if qd is None:  # generation-only server: its lane's backlog
                qd = (snap.get("generation") or {}).get("queue_depth")
            queue_depth = int(qd or 0)
            mem = ((snap.get("telemetry") or {}).get("memory") or {})
            if isinstance(mem, dict) and "min_headroom" in mem:
                headroom = mem["min_headroom"]
            # sharded lane: the replica is a planned mesh of M chips —
            # carried on the table so capacity math counts chips
            m = snap.get("mesh")
            if isinstance(m, dict):
                mesh = m
        except Exception:
            pass  # health answered; load detail is best-effort
        return health, queue_depth, headroom, mesh

    def scrape_once(self):
        """One parallel load/health sweep over every replica; applies
        state transitions (joining → up on first healthy scrape,
        unreachable → ``down``). Called by the background scraper every
        ``MXNET_GATEWAY_SCRAPE_MS``; tests call it directly."""
        with self._lock:
            targets = [(r.id, r.url) for r in self._replicas.values()]
        scraped = self._fan_out(targets, self._scrape_replica)
        with self._lock:
            for rid, _url in targets:
                rep = self._replicas.get(rid)
                if rep is None:
                    continue
                out = scraped.get(rid)
                if out is None:
                    rep.scrape_failures += 1
                    if rep.health != "down":
                        rep.health = "down"
                        self._event("replica_down", replica=rid,
                                    url=rep.url)
                    continue
                health, queue_depth, headroom, mesh = out
                rep.scrape_failures = 0
                came_up = (rep.health != "ok" and health == "ok")
                rep.health = health
                rep.queue_depth = queue_depth
                rep.headroom = headroom
                if mesh is not None:
                    rep.mesh = mesh
                if rep.state == JOINING and health == "ok":
                    rep.state = UP
                    self._event("replica_up", replica=rid, url=rep.url)
                elif came_up and rep.state == UP:
                    self._event("replica_healthy", replica=rid)
        return self.replica_table()

    def _scrape_loop(self):
        while not self._closing:
            try:
                self.scrape_once()
            except Exception:
                pass  # the scraper must outlive any one bad sweep
            self._scrape_wake.wait(self._scrape_s)
            self._scrape_wake.clear()

    # ---- routing ----------------------------------------------------------
    def _score(self, rep):
        # queue depth is the replica's own backlog; inflight/pins are the
        # gateway's live view between scrapes; degraded costs extra so a
        # breaker-open/low-HBM replica only takes traffic when everyone
        # else is worse; low routed count breaks ties (spread when idle)
        score = rep.queue_depth + rep.inflight + 2 * rep.pins
        if rep.health == "degraded":
            score += 4
        if rep.headroom is not None and rep.headroom < 0.1:
            score += 4
        return score

    def _pick(self, exclude):
        """Least-loaded routable replica not in ``exclude``, with its
        breaker admission ticket. Returns (replica, admission) or
        (None, None)."""
        with self._lock:
            candidates = [r for r in self._replicas.values()
                          if r.id not in exclude and r.state == UP
                          and r.health not in ("down", "draining")]
            candidates.sort(key=lambda r: (self._score(r), r.routed, r.id))
            for rep in candidates:
                admission = rep.breaker.allow()
                if not admission:
                    continue  # ejected (open) — skip without counting
                rep.inflight += 1
                rep.routed += 1
                return rep, admission
        return None, None

    def _release(self, rep):
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)

    def _note_outcome(self, rep, admission, ok, fault=True):
        """Feed the replica's breaker and translate its state changes
        into ejection/readmission events."""
        before = rep.breaker.state
        if ok:
            rep.breaker.record_success(admission)
        elif fault:
            with self._lock:
                rep.failures += 1
            rep.breaker.record_failure(admission)
        else:
            rep.breaker.release(admission)
        after = rep.breaker.state
        if before != "open" and after == "open":
            self.metrics.count("ejections")
            self._event("replica_ejected", replica=rep.id,
                        failures=rep.failures)
        elif before == "half_open" and after == "closed":
            self.metrics.count("readmissions")
            self._event("replica_readmitted", replica=rep.id)

    def _forward_once(self, rep, path, body, rid):
        """One buffered POST to one replica. Returns (status, headers,
        body_bytes); raises ``OSError``-family on transport failure."""
        u = urllib.parse.urlsplit(rep.url)
        conn = http.client.HTTPConnection(
            u.hostname, u.port, timeout=self._forward_timeout_s)
        try:
            conn.request("POST", path, body=body, headers={
                "Content-Type": "application/json",
                "X-Request-Id": rid,
                "Content-Length": str(len(body)),
            })
            resp = conn.getresponse()
            data = resp.read()
            headers = {}
            for k in ("X-Model-Version", "Retry-After", "X-Request-Id",
                      "Content-Type"):
                v = resp.headers.get(k)
                if v is not None:
                    headers[k] = v
            return resp.status, headers, data
        finally:
            conn.close()

    def forward_predict(self, path, body, rid):
        """Route one idempotent ``/predict`` with failover: pick the
        least-loaded replica, forward, and on connect/5xx failure
        re-route to the next-best under the retry policy. Returns
        (status, headers, body). Raises :class:`NoRoutableReplica` /
        :class:`~mxnet_tpu.resilience.retry.RetryExhausted` for the
        handler to map to 503."""
        tried = set()
        state = {"attempt": 0}

        def attempt():
            state["attempt"] += 1
            _chaos.point("gateway.forward")
            rep, admission = self._pick(tried)
            if rep is None:
                if not tried:
                    self.metrics.count("no_replica")
                    raise NoRoutableReplica(
                        "no routable replica (%d known)"
                        % len(self._replicas))
                # everyone was tried this round: let the policy's backoff
                # buy recovery time, then try the whole set again
                tried.clear()
                raise ReplicaUnavailable("all replicas tried; retrying")
            tried.add(rep.id)
            failing_over = state["attempt"] > 1
            if failing_over:
                self.metrics.count("failovers")
                _trace.instant("gateway.failover", request_id=rid,
                               replica=rep.id, attempt=state["attempt"])
            span = ("gateway.failover" if failing_over
                    else "gateway.forward")
            try:
                with _trace.span(span, request_id=rid, replica=rep.id):
                    status, headers, data = self._forward_once(
                        rep, path, body, rid)
            except OSError as e:
                self._note_outcome(rep, admission, ok=False)
                raise ReplicaUnavailable(
                    "replica %d (%s) unreachable: %s: %s"
                    % (rep.id, rep.url, type(e).__name__, e)) from e
            finally:
                self._release(rep)
            if status >= 500:
                # 503 is backpressure/drain (not a model fault — don't
                # burn the breaker), everything else 5xx is; both
                # re-route: /predict is idempotent
                self._note_outcome(rep, admission, ok=False,
                                   fault=status not in (503,))
                raise ReplicaUnavailable(
                    "replica %d replied %d" % (rep.id, status))
            self._note_outcome(rep, admission, ok=True)
            return status, headers, data

        with _trace.span("gateway.route", request_id=rid, path=path):
            if self._retry is not None:
                return self._retry.call(attempt)
            return attempt()

    def forward_profile(self, rep, seconds, body, rid):
        """Proxy one ``POST /debug/profile?seconds=N`` to a named
        replica, attaching the gateway's admin token and widening the
        socket timeout past the capture window (plus slack for trace
        finalize + checksumming). Returns ``(status, headers, body)``;
        transport failure maps to 502 — the replica may still be fine,
        only this capture hop failed."""
        max_s = float(_config.get("MXNET_PROF_CAPTURE_MAX_S") or 60.0)
        timeout = min(seconds, max_s) + max(10.0,
                                            self._forward_timeout_s)
        u = urllib.parse.urlsplit(rep.url)
        conn = http.client.HTTPConnection(u.hostname, u.port,
                                          timeout=timeout)
        headers = {"Content-Type": "application/json",
                   "X-Request-Id": rid,
                   "Content-Length": str(len(body))}
        if self._admin_token:
            headers["X-Admin-Token"] = self._admin_token
        try:
            with _trace.span("gateway.profile", request_id=rid,
                             replica=rep.id, seconds=seconds):
                conn.request("POST",
                             "/debug/profile?seconds=%s" % seconds,
                             body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            out_headers = {}
            ctype = resp.headers.get("Content-Type")
            if ctype:
                out_headers["Content-Type"] = ctype
            return resp.status, out_headers, data
        except OSError as e:
            return 502, {}, json.dumps(
                {"error": "replica %d profile capture failed: %s: %s"
                          % (rep.id, type(e).__name__, e)}).encode()
        finally:
            conn.close()

    # ---- streamed /generate (sticky) --------------------------------------
    def _pin(self, rep):
        with self._lock:
            rep.pins += 1

    def _unpin(self, rep):
        with self._lock:
            rep.pins = max(0, rep.pins - 1)

    def stream_generate(self, handler, path, body, rid):
        """Route one ``/generate``: sticky — the stream pins its replica
        end-to-end (the KV slot lives there). Pre-response failures fail
        over to the next-best replica (nothing streamed yet, the prompt
        is resubmittable); once streaming, replica death surfaces the
        protocol's in-band ``{"error": ...}`` line and frees the pin."""
        tried = set()
        self.metrics.count("streams")
        with _trace.span("gateway.route", request_id=rid, path=path,
                         stream=True):
            for attempt_n in range(max(1, len(self._replicas) + 1)):
                rep, admission = self._pick(tried)
                if rep is None:
                    handler._reply(503, {"error": "no routable replica"},
                                   headers={"Retry-After": "1"})
                    self.metrics.count("no_replica")
                    return
                tried.add(rep.id)
                if attempt_n > 0:
                    self.metrics.count("failovers")
                    _trace.instant("gateway.failover", request_id=rid,
                                   replica=rep.id, attempt=attempt_n + 1)
                self._pin(rep)
                try:
                    done = self._stream_from(handler, rep, admission,
                                             path, body, rid)
                finally:
                    self._unpin(rep)
                    self._release(rep)
                if done:
                    return
            handler._reply(503, {"error": "all replicas failed"},
                           headers={"Retry-After": "1"})

    def _stream_from(self, handler, rep, admission, path, body, rid):
        """Attempt the stream on one pinned replica. Returns True when a
        reply (success or relayed typed failure) reached the client —
        False means nothing was sent and the caller may fail over."""
        u = urllib.parse.urlsplit(rep.url)
        conn = http.client.HTTPConnection(
            u.hostname, u.port, timeout=self._forward_timeout_s)
        t0 = time.monotonic()
        try:
            try:
                conn.request("POST", path, body=body, headers={
                    "Content-Type": "application/json",
                    "X-Request-Id": rid,
                    "Content-Length": str(len(body)),
                })
                resp = conn.getresponse()
            except OSError as e:
                self._note_outcome(rep, admission, ok=False)
                _trace.instant("gateway.stream_connect_failed",
                               request_id=rid, replica=rep.id,
                               error=type(e).__name__)
                return False  # nothing sent: caller fails over
            if resp.status != 200:
                data = resp.read()
                if resp.status >= 500 and resp.status != 504:
                    # 5xx pre-stream: prompt never started decoding —
                    # safe to fail over (503 = busy/drain, not a fault)
                    self._note_outcome(rep, admission, ok=False,
                                       fault=resp.status != 503)
                    return False
                # typed client-facing failure (400/404/504): relay as-is
                self._note_outcome(rep, admission, ok=True)
                headers = {k: v for k, v in (
                    ("X-Model-Version",
                     resp.headers.get("X-Model-Version")),
                    ("Retry-After", resp.headers.get("Retry-After")),
                ) if v is not None}
                handler._reply_raw(resp.status, data, headers)
                return True
            # 200: commit to chunked NDJSON relay
            handler.send_response(200)
            handler.send_header("Content-Type", "application/x-ndjson")
            handler.send_header("X-Request-Id", rid)
            mv = resp.headers.get("X-Model-Version")
            if mv is not None:
                handler.send_header("X-Model-Version", mv)
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()
            finished = False
            client_gone = False
            try:
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    try:
                        handler.wfile.write(b"%x\r\n" % len(line))
                        handler.wfile.write(line)
                        handler.wfile.write(b"\r\n")
                        handler.wfile.flush()
                    except OSError:
                        client_gone = True
                        break
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        obj = {}
                    if obj.get("done") or obj.get("error"):
                        finished = True
                        break
            except (OSError, http.client.HTTPException) as e:
                # replica died mid-stream: in-band error (the status line
                # is long gone), pin released by the caller, breaker fed
                self.metrics.count("stream_errors")
                self._note_outcome(rep, admission, ok=False)
                self._event("stream_replica_lost", replica=rep.id,
                            request_id=rid, error=type(e).__name__)
                try:
                    msg = json.dumps(
                        {"error": "replica %d lost mid-stream: %s"
                                  % (rep.id, type(e).__name__)}) + "\n"
                    data = msg.encode("utf-8")
                    handler.wfile.write(b"%x\r\n" % len(data))
                    handler.wfile.write(data)
                    handler.wfile.write(b"\r\n0\r\n\r\n")
                except OSError:
                    pass
                handler.close_connection = True
                self.metrics.record_request(time.monotonic() - t0,
                                            ok=False)
                return True
            if client_gone:
                # the consumer went away: close toward the replica too so
                # its cancel sweep frees the KV slot; not a replica fault
                self._note_outcome(rep, admission, ok=True)
                handler.close_connection = True
                return True
            if not finished:
                # EOF without a done/error line = replica vanished
                # between chunks — same in-band contract
                self.metrics.count("stream_errors")
                self._note_outcome(rep, admission, ok=False)
                self._event("stream_replica_lost", replica=rep.id,
                            request_id=rid, error="eof")
                try:
                    msg = json.dumps(
                        {"error": "replica %d lost mid-stream: eof"
                                  % rep.id}) + "\n"
                    data = msg.encode("utf-8")
                    handler.wfile.write(b"%x\r\n" % len(data))
                    handler.wfile.write(data)
                    handler.wfile.write(b"\r\n0\r\n\r\n")
                except OSError:
                    pass
                handler.close_connection = True
                self.metrics.record_request(time.monotonic() - t0,
                                            ok=False)
                return True
            try:
                handler.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
            self._note_outcome(rep, admission, ok=True)
            self.metrics.record_request(time.monotonic() - t0, ok=True)
            return True
        finally:
            conn.close()

    # ---- drain / rolling restart ------------------------------------------
    def mark_draining(self, rid, call_drain=True):
        """Stop routing to replica ``rid`` (its in-flight requests and
        pinned streams keep completing), and — with ``call_drain`` — tell
        the replica itself via ``GET /drain`` so its own ``/healthz``
        flips before any supervisor signal lands."""
        rep = self.replica(rid)
        if rep is None:
            return None
        with self._lock:
            rep.state = DRAINING
        self.metrics.count("drains")
        self._event("replica_draining", replica=rid)
        if call_drain:
            try:
                req = urllib.request.Request(rep.url + "/drain")
                if self._admin_token:
                    req.add_header("X-Admin-Token", self._admin_token)
                with urllib.request.urlopen(
                        req, timeout=self._connect_timeout_s) as r:
                    r.read()
            except Exception:
                pass  # unreachable replica is already as drained as it gets
        return rep

    def wait_drained(self, rid, timeout_s=None, poll_s=0.02):
        """Block until replica ``rid`` has zero gateway-tracked in-flight
        requests and pinned streams (bounded). True on clean drain."""
        if timeout_s is None:
            timeout_s = _config.get("MXNET_GATEWAY_DRAIN_TIMEOUT_MS") / 1e3
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            rep = self.replica(rid)
            if rep is None:
                return True
            with self._lock:
                clear = rep.inflight == 0 and rep.pins == 0
            if clear:
                return True
            time.sleep(poll_s)
        return False

    def readmit(self, rid, ready_timeout_s=60.0, poll_s=0.05):
        """Health-gated readmission: poll the replica's ``/healthz``
        until ``ok``, then route to it again (fresh breaker — the old
        process's failure history doesn't taint the new one)."""
        rep = self.replica(rid)
        if rep is None:
            return False
        deadline = time.monotonic() + ready_timeout_s
        while time.monotonic() < deadline:
            out = self._scrape_replica(rep.url)
            if out is not None and out[0] == "ok":
                with self._lock:
                    old = rep.breaker
                    rep.breaker = self._mk_breaker(rep.id)
                    rep.state = UP
                    rep.health = "ok"
                    rep.queue_depth = out[1]
                    rep.failures = 0
                    rep.generation += 1
                old.deregister()
                self._event("replica_readmitted", replica=rid,
                            generation=rep.generation)
                return True
            time.sleep(poll_s)
        self._event("readmit_timeout", replica=rid)
        return False

    def rolling_restart(self, backend=None, drain_timeout_s=None,
                        ready_timeout_s=60.0):
        """Drain-aware rolling restart of the whole fleet, one replica at
        a time: mark draining (routing stops) → replica ``/drain`` →
        wait for in-flight + pins to clear → ``backend.restart`` (lands
        on the AOT zero-compile path when artifacts are published) →
        health-gated readmission. Returns a per-replica report; zero
        requests are dropped because traffic always has somewhere else
        to go before the replica loses its listener."""
        backend = backend or self._backend
        if backend is None:
            raise ValueError("rolling_restart needs a backend "
                             "(spawn/restart/stop provider)")
        report = []
        for rid in sorted(r.id for r in self.replicas()):
            rep = self.replica(rid)
            if rep is None:
                continue
            t0 = time.monotonic()
            self.mark_draining(rid)
            drained = self.wait_drained(rid, timeout_s=drain_timeout_s)
            self._event("replica_restarting", replica=rid,
                        drained=drained)
            try:
                new_url = backend.restart(rep)
            except Exception as e:
                # the old process is already gone — don't leave the
                # replica parked in DRAINING (which both routing AND the
                # supervisor's crash watch skip forever): back to
                # JOINING, so a supervisor respawns the dead process and
                # the scrape loop health-gates any comeback to UP
                with self._lock:
                    rep.state = JOINING
                self._event("restart_failed", replica=rid,
                            error="%s: %s" % (type(e).__name__, e))
                report.append({"replica": rid, "ok": False,
                               "error": str(e)})
                continue
            if new_url:
                with self._lock:
                    rep.url = new_url.rstrip("/")
            ok = self.readmit(rid, ready_timeout_s=ready_timeout_s)
            report.append({"replica": rid, "ok": ok,
                           "drained": drained,
                           "seconds": time.monotonic() - t0})
        self.metrics.count("rolling_restarts")
        self._event("rolling_restart_done",
                    ok=all(r["ok"] for r in report))
        return report

    # ---- surface ----------------------------------------------------------
    def health(self):
        """Gateway ``/healthz``: ``ok`` while at least one replica is
        routable, ``degraded`` otherwise — the signal an outer LB (or a
        human) keys off; per-replica detail rides along."""
        table = self.replica_table()
        ready = sum(1 for r in table.values()
                    if r["state"] == UP and r["health"] == "ok"
                    and r["breaker"] != "open")
        return {"status": "ok" if ready > 0 else "degraded",
                "ready_replicas": ready, "replicas": table}

    @property
    def address(self):
        return self._httpd.server_address[:2]

    @property
    def url(self):
        host, port = self.address
        return "http://%s:%d" % (host, port)

    def start(self):
        """Serve in a background thread (plus the load scraper, unless
        ``scrape_ms=0``); one synchronous scrape runs first so initial
        replicas can come up before the first request arrives."""
        if self._thread is None:
            if self._replicas:
                try:
                    self.scrape_once()
                except Exception:
                    pass
            if self._scrape_s > 0 and self._scrape_thread is None:
                self._scrape_thread = threading.Thread(
                    target=self._scrape_loop, daemon=True,
                    name="gateway-scraper")
                self._scrape_thread.start()
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="gateway")
            self._thread.start()
        return self

    def close(self):
        self._closing = True
        self._scrape_wake.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self._scrape_thread is not None:
            self._scrape_thread.join(5.0)
            self._scrape_thread = None
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            rep.breaker.deregister()
        self.metrics.unbind_profiler()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

class Autoscaler:
    """Grow/shrink the replica set on queue-depth / p99-SLO burn.

    Signals (evaluated per :meth:`tick` — production runs ticks on a
    background thread every ``interval_s``; tests call :meth:`tick`
    directly, so schedules are asserted without sleeping):

    - **burn**: gateway-observed p99 over the sliding window above
      ``slo_p99_ms`` (``MXNET_GATEWAY_SLO_P99_MS``; 0 disables), OR mean
      scraped queue depth per ready *chip* (a sharded replica counts
      its mesh size, keeping capacity math honest) above ``queue_high``
      (``MXNET_GATEWAY_QUEUE_HIGH``). ``burn_ticks`` consecutive burn
      ticks → spawn one replica through the backend (it joins
      health-gated, like any other replica).
    - **idle**: p99 under half the SLO and queue depth ≤ 1 for
      ``idle_ticks`` consecutive ticks → drain one replica through the
      same drain machinery rolling restarts use, then ``backend.stop``.

    Hysteresis: every action resets both streaks (one decision per
    sustained signal, not one per tick), and the set never leaves
    ``[min_replicas, max_replicas]``.
    """

    def __init__(self, gateway, backend=None, min_replicas=None,
                 max_replicas=None, slo_p99_ms=None, queue_high=None,
                 burn_ticks=3, idle_ticks=6, interval_s=1.0):
        self.gateway = gateway
        self.backend = backend or gateway._backend
        if self.backend is None:
            raise ValueError("Autoscaler needs a backend (spawn/stop)")
        self.min_replicas = (_config.get("MXNET_GATEWAY_MIN_REPLICAS")
                             if min_replicas is None else int(min_replicas))
        self.max_replicas = (_config.get("MXNET_GATEWAY_MAX_REPLICAS")
                             if max_replicas is None else int(max_replicas))
        self.slo_p99_ms = (_config.get("MXNET_GATEWAY_SLO_P99_MS")
                           if slo_p99_ms is None else float(slo_p99_ms))
        self.queue_high = (_config.get("MXNET_GATEWAY_QUEUE_HIGH")
                           if queue_high is None else int(queue_high))
        self.burn_ticks = int(burn_ticks)
        self.idle_ticks = int(idle_ticks)
        self.interval_s = float(interval_s)
        self._burn = 0
        self._idle = 0
        self._thread = None
        self._stop = threading.Event()

    # ---- signals ----------------------------------------------------------
    def evaluate(self):
        """Current signal values (no side effects): the decision a
        :meth:`tick` would act on — exposed for tests and the event
        log."""
        gw = self.gateway
        ready = gw.ready_replicas()
        n = len(ready)
        p99 = gw.metrics.p99_ms()
        # capacity unit is the CHIP, not the replica: a sharded replica
        # is a planned mesh of M chips, so its backlog divides by M —
        # otherwise one 8-chip replica reads 8x busier than eight
        # 1-chip replicas holding the same queue
        chips = sum(r.chips for r in ready)
        mean_q = (sum(r.queue_depth for r in ready) / chips) if chips \
            else 0.0
        slo_burn = self.slo_p99_ms > 0 and p99 > self.slo_p99_ms
        queue_burn = n > 0 and mean_q > self.queue_high
        idle = (mean_q <= 1.0
                and (self.slo_p99_ms <= 0 or p99 < self.slo_p99_ms / 2))
        return {"ready": n, "total": len(gw.replicas()), "p99_ms": p99,
                "chips": chips, "mean_queue_depth": mean_q,
                "slo_burn": slo_burn, "queue_burn": queue_burn,
                "idle": idle}

    def tick(self):
        """One evaluation step; applies at most one scale action.
        Returns ("up"|"down"|None, signals)."""
        sig = self.evaluate()
        action = None
        if sig["slo_burn"] or sig["queue_burn"]:
            self._burn += 1
            self._idle = 0
            if self._burn >= self.burn_ticks \
                    and sig["total"] < self.max_replicas:
                action = "up"
        elif sig["idle"] and sig["ready"] > 0:
            self._idle += 1
            self._burn = 0
            if self._idle >= self.idle_ticks \
                    and sig["ready"] > self.min_replicas:
                action = "down"
        else:
            self._burn = 0
            self._idle = 0
        if action == "up":
            self.scale_up(reason=sig)
        elif action == "down":
            self.scale_down(reason=sig)
        return action, sig

    # ---- actions ----------------------------------------------------------
    def scale_up(self, reason=None):
        """Spawn one replica through the backend; it joins health-gated
        (no traffic until its ``/healthz`` turns ok)."""
        spawned = self.backend.spawn()
        url, meta = spawned if isinstance(spawned, tuple) else (spawned,
                                                                None)
        rep = self.gateway.add_replica(url, meta=meta)
        self.gateway.metrics.count("scale_ups")
        self.gateway._event("scale_up", replica=rep.id, url=rep.url,
                            signals=reason)
        self._burn = 0
        self._idle = 0
        return rep

    def scale_down(self, reason=None):
        """Drain the least-loaded ready replica (same machinery as the
        rolling restart) and stop it through the backend."""
        gw = self.gateway
        ready = gw.ready_replicas()
        if len(ready) <= self.min_replicas:
            return None
        # least-loaded loses: its in-flight set is the cheapest to drain
        victim = sorted(ready, key=lambda r: (gw._score(r), -r.id))[0]
        gw.mark_draining(victim.id)
        gw.wait_drained(victim.id)
        try:
            self.backend.stop(victim)
        except Exception as e:
            gw._event("scale_down_failed", replica=victim.id,
                      error="%s: %s" % (type(e).__name__, e))
        gw.remove_replica(victim.id)
        gw.metrics.count("scale_downs")
        gw._event("scale_down", replica=victim.id, signals=reason)
        self._burn = 0
        self._idle = 0
        return victim

    # ---- background loop --------------------------------------------------
    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="gateway-autoscaler")
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                pass  # one bad tick must not kill the control loop

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
