"""ModelServer: stdlib HTTP front-end for the serving stack.

Role parity: MXNet Model Server's REST surface (``/predictions``,
``/ping``, ``/metrics``), reduced to the stdlib so the whole serving path —
HTTP → DynamicBatcher → InferenceEngine → XLA — is exercisable end-to-end
with zero extra dependencies. ``ThreadingHTTPServer`` gives one thread per
in-flight request, which is exactly the concurrency shape the batcher
coalesces.

Endpoints (JSON):

- ``POST /predict`` — body ``{"data": [...]}`` (one sample, no batch
  axis) or ``{"inputs": [[...], ...]}`` for multi-input models; optional
  ``"dtype"`` (default float32) and ``"timeout_ms"``. Response
  ``{"output": [...]}`` (or ``{"outputs": [...]}``). Typed failures map
  to load-balancer-friendly codes: ServerBusy→503, DeadlineExceeded→504,
  malformed input→400.
- ``GET /healthz`` — liveness.
- ``GET /metrics`` — ``ServingMetrics.snapshot()`` (QPS, latency
  percentiles, occupancy, queue depth, executor-cache counters).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from .batcher import (DeadlineExceeded, DynamicBatcher, ServerBusy,
                      ServerClosed)
from .engine import InferenceEngine
from .metrics import ServingMetrics

__all__ = ["ModelServer"]


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet_tpu_serving/0.1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: metrics replace access logs
        pass

    def _reply(self, code, payload):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        srv = self.server.model_server
        if self.path == "/healthz":
            self._reply(200, {"status": "ok"})
        elif self.path == "/metrics":
            self._reply(200, srv.metrics.snapshot())
        else:
            self._reply(404, {"error": "unknown path %s" % self.path})

    def do_POST(self):  # noqa: N802
        srv = self.server.model_server
        if self.path != "/predict":
            self._reply(404, {"error": "unknown path %s" % self.path})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if "inputs" in payload:
                raw = payload["inputs"]
            elif "data" in payload:
                raw = [payload["data"]]
            else:
                raise ValueError('body needs "data" or "inputs"')
            dtype = payload.get("dtype", "float32")
            inputs = [_np.asarray(x, dtype=dtype) for x in raw]
            timeout_ms = payload.get("timeout_ms")
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            row = srv.batcher.predict(*inputs, timeout_ms=timeout_ms)
        except ServerBusy as e:
            self._reply(503, {"error": str(e)})
            return
        except DeadlineExceeded as e:
            self._reply(504, {"error": str(e)})
            return
        except ServerClosed as e:
            self._reply(503, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — model failure
            self._reply(500, {"error": "%s: %s" % (type(e).__name__, e)})
            return
        if isinstance(row, tuple):
            self._reply(200, {"outputs": [_np.asarray(r).tolist()
                                          for r in row]})
        else:
            self._reply(200, {"output": _np.asarray(row).tolist()})


class ModelServer:
    """Wire engine + batcher + metrics behind one HTTP listener.

    ``model`` may be an :class:`InferenceEngine` (pre-configured buckets /
    warmup) or any batched callable, in which case an engine is built with
    ``buckets``. ``port=0`` picks an ephemeral port (tests).
    """

    def __init__(self, model, host="127.0.0.1", port=8080,
                 buckets=None, jit=True, max_batch_size=32,
                 max_latency_ms=5.0, max_queue_size=128,
                 default_timeout_ms=None, metrics=None,
                 bind_profiler=True):
        self.metrics = metrics or ServingMetrics()
        if isinstance(model, InferenceEngine):
            self.engine = model
            self.metrics.set_cache_stats_fn(self.engine.stats)
        else:
            from .engine import DEFAULT_BUCKETS
            self.engine = InferenceEngine(
                model, buckets=buckets or DEFAULT_BUCKETS, jit=jit,
                metrics=self.metrics)
        if bind_profiler:
            self.metrics.bind_profiler()
        self.batcher = DynamicBatcher(
            self.engine, max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms, max_queue_size=max_queue_size,
            default_timeout_ms=default_timeout_ms, metrics=self.metrics)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.model_server = self
        self._thread = None

    @property
    def address(self):
        """(host, port) actually bound — resolves port=0."""
        return self._httpd.server_address[:2]

    @property
    def url(self):
        host, port = self.address
        return "http://%s:%d" % (host, port)

    def start(self):
        """Serve in a background thread; returns self (chainable)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="model-server")
            self._thread.start()
        return self

    def serve(self):
        """Blocking serve (Ctrl-C to stop)."""
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self, drain=True):
        """Stop the listener, then shut the batcher down (draining
        in-flight work by default)."""
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self.batcher.close(drain=drain)
        self.metrics.unbind_profiler()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
