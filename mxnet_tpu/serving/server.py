"""ModelServer: stdlib HTTP front-end for the serving stack.

Role parity: MXNet Model Server's REST surface (``/predictions``,
``/ping``, ``/metrics``), reduced to the stdlib so the whole serving path —
HTTP → DynamicBatcher → InferenceEngine → XLA — is exercisable end-to-end
with zero extra dependencies. ``ThreadingHTTPServer`` gives one thread per
in-flight request, which is exactly the concurrency shape the batcher
coalesces.

Endpoints (JSON):

- ``POST /predict`` — body ``{"data": [...]}`` (one sample, no batch
  axis) or ``{"inputs": [[...], ...]}`` for multi-input models; optional
  ``"dtype"`` (default float32) and ``"timeout_ms"``. Response
  ``{"output": [...]}`` (or ``{"outputs": [...]}``). Typed failures map
  to load-balancer-friendly codes: ServerBusy→503, DeadlineExceeded→504,
  malformed input→400.
- ``GET /healthz`` — liveness + degradation: ``{"status": "ok"}`` in
  normal service, ``"degraded"`` (with breaker state) while the circuit
  breaker is open/half-open, ``"draining"`` during shutdown — load
  balancers key off the status field to drain the instance.
- ``GET /metrics`` — ``ServingMetrics.snapshot()`` (QPS, latency
  percentiles, occupancy, queue depth, executor-cache counters, retry
  counters, breaker state).

Resilience: model failures feed a
:class:`~mxnet_tpu.resilience.breaker.CircuitBreaker`; while it is open,
``/predict`` fast-fails with 503 + ``Retry-After`` instead of queueing
doomed work, then half-open probes let real traffic close it again.

Tracing: every ``/predict`` gets an ``X-Request-Id`` (honored from the
incoming header, minted otherwise) echoed on the response, and — while
``mxnet_tpu.observability`` tracing is on — a ``serving.http`` root span
carrying it. The request's queue wait, batch assembly, and engine
execution are recorded as linked spans (same trace id) even though they
run on the batcher worker thread, so a p99 outlier in ``profiler.dump()``
decomposes into its phases instead of being one opaque latency number.
"""
from __future__ import annotations

import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from ..observability import tracer as _trace
from ..resilience import elastic as _elastic
from ..resilience import guardrails as _guardrails
from ..resilience import retry as _retry
from ..resilience.breaker import CircuitBreaker
from .batcher import (DeadlineExceeded, DynamicBatcher, ServerBusy,
                      ServerClosed)
from .engine import InferenceEngine
from .metrics import ServingMetrics

__all__ = ["ModelServer"]


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet_tpu_serving/0.1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: metrics replace access logs
        pass

    def _reply(self, code, payload, headers=None):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        # a keep-alive connection reuses this handler across requests: a
        # GET after a POST must not echo the POST's stale request id
        self._request_id = None
        srv = self.server.model_server
        if self.path == "/healthz":
            self._reply(200, srv.health())
        elif self.path == "/metrics":
            self._reply(200, srv.metrics.snapshot())
        else:
            self._reply(404, {"error": "unknown path %s" % self.path})

    def do_POST(self):  # noqa: N802
        # the request id propagates: honored from the client's header
        # (upstream tracing), minted otherwise; echoed on every reply and
        # attached to the request's whole span chain
        rid = self.headers.get("X-Request-Id") or uuid.uuid4().hex[:16]
        self._request_id = rid
        with _trace.span("serving.http", request_id=rid, path=self.path):
            self._handle_post(rid)

    def _handle_post(self, rid):
        srv = self.server.model_server
        # consume the body FIRST: an early reply with the body still unread
        # desyncs HTTP/1.1 keep-alive (the next request on the connection
        # would be parsed starting at the leftover body bytes)
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length < 0:  # read(-1) would block until client EOF
                raise ValueError("negative Content-Length")
            body = self.rfile.read(length)
        except (ValueError, TypeError):
            self.close_connection = True  # unknown length: can't resync
            self._reply(400, {"error": "bad Content-Length"})
            return
        if self.path != "/predict":
            self._reply(404, {"error": "unknown path %s" % self.path})
            return
        if srv.draining:
            # shutdown in progress: shed new work BEFORE the socket goes
            # away so clients get a clean 503, not a connection reset
            self._reply(503, {"error": "server draining"},
                        headers={"Retry-After": "1"})
            return
        # parse BEFORE breaker admission: a malformed body (400) must
        # never hold a half-open probe slot
        try:
            payload = json.loads(body or b"{}")
            if "inputs" in payload:
                raw = payload["inputs"]
            elif "data" in payload:
                raw = [payload["data"]]
            else:
                raise ValueError('body needs "data" or "inputs"')
            dtype = payload.get("dtype", "float32")
            inputs = [_np.asarray(x, dtype=dtype) for x in raw]
            timeout_ms = payload.get("timeout_ms")
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        breaker = srv.breaker
        admission = breaker.allow() if breaker is not None else True
        if not admission:
            retry_after = max(1, int(round(breaker.retry_after_s())))
            snap = breaker.snapshot()
            self._reply(503, {"error": "circuit open: %s" % snap["state"],
                              "breaker": snap},
                        headers={"Retry-After": str(retry_after)})
            return
        try:
            row = srv.batcher.predict(*inputs, timeout_ms=timeout_ms,
                                      request_id=rid)
        except ServerBusy as e:
            # backpressure, not a model fault: the breaker must not trip
            if breaker is not None:
                breaker.release(admission)
            self._reply(503, {"error": str(e)},
                        headers={"Retry-After": "1"})
            return
        except DeadlineExceeded as e:
            if breaker is not None:
                breaker.release(admission)
            self._reply(504, {"error": str(e)})
            return
        except ServerClosed as e:
            if breaker is not None:
                breaker.release(admission)
            self._reply(503, {"error": str(e)},
                        headers={"Retry-After": "1"})
            return
        except Exception as e:  # noqa: BLE001 — model failure
            if breaker is not None:
                breaker.record_failure(admission)
            self._reply(500, {"error": "%s: %s" % (type(e).__name__, e)})
            return
        if breaker is not None:
            breaker.record_success(admission)
        if isinstance(row, tuple):
            self._reply(200, {"outputs": [_np.asarray(r).tolist()
                                          for r in row]})
        else:
            self._reply(200, {"output": _np.asarray(row).tolist()})


class ModelServer:
    """Wire engine + batcher + metrics + breaker behind one HTTP listener.

    ``model`` may be an :class:`InferenceEngine` (pre-configured buckets /
    warmup) or any batched callable, in which case an engine is built with
    ``buckets``. ``port=0`` picks an ephemeral port (tests).

    ``breaker=None`` (default) builds a :class:`CircuitBreaker` from the
    ``MXNET_BREAKER_*`` env knobs (set ``MXNET_BREAKER_FAILURE_THRESHOLD``
    <= 0 to disable); pass a configured breaker, or ``False`` to disable
    explicitly. ``retry_policy`` is forwarded to the batcher — the single
    retry layer in this stack; an engine built here gets
    ``retry_policy=False`` (pass a pre-built engine to layer differently).
    """

    def __init__(self, model, host="127.0.0.1", port=8080,
                 buckets=None, jit=True, max_batch_size=32,
                 max_latency_ms=5.0, max_queue_size=128,
                 default_timeout_ms=None, metrics=None,
                 breaker=None, retry_policy=None,
                 bind_profiler=True):
        self.metrics = metrics or ServingMetrics()
        if isinstance(model, InferenceEngine):
            self.engine = model
            self.metrics.set_cache_stats_fn(self.engine.stats)
        else:
            from .engine import DEFAULT_BUCKETS
            # retry lives at the batcher layer here (it re-runs the whole
            # coalesced batch); a second engine-level policy underneath
            # would only multiply attempts and split the counters
            self.engine = InferenceEngine(
                model, buckets=buckets or DEFAULT_BUCKETS, jit=jit,
                metrics=self.metrics, retry_policy=False)
        if breaker is None:
            from .. import config as _config
            threshold = _config.get("MXNET_BREAKER_FAILURE_THRESHOLD")
            breaker = CircuitBreaker(
                failure_threshold=threshold,
                recovery_ms=_config.get("MXNET_BREAKER_RECOVERY_MS"),
                half_open_probes=_config.get(
                    "MXNET_BREAKER_HALF_OPEN_PROBES"),
                name="serving") if threshold > 0 else False
        self.breaker = breaker or None
        if self.breaker is not None:
            self.metrics.set_gauge_fn("breaker", self.breaker.snapshot)
        self.metrics.set_gauge_fn("retry", _retry.all_stats)
        self.metrics.set_gauge_fn("guardrails", _guardrails.all_stats)
        # elastic membership: the LB-visible view of "how many hosts does
        # this job still have" plus pending-preemption state
        self.metrics.set_gauge_fn("elastic", _elastic.membership_gauge)
        from ..parallel import datafeed as _datafeed
        self.metrics.set_gauge_fn("datafeed", _datafeed.feed_stats)
        # trace-derived per-phase latency histograms on /metrics: the
        # timeline's aggregate view without parsing the dumped JSON
        self.metrics.set_gauge_fn("trace", _trace.summary_gauge)
        if bind_profiler:
            self.metrics.bind_profiler()
        self._draining = False
        self.batcher = DynamicBatcher(
            self.engine, max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms, max_queue_size=max_queue_size,
            default_timeout_ms=default_timeout_ms, metrics=self.metrics,
            retry_policy=retry_policy)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.model_server = self
        self._thread = None

    @property
    def draining(self):
        return self._draining

    def health(self):
        """The ``/healthz`` payload: ``ok`` | ``degraded`` | ``draining``
        (+ breaker state when degraded) — the drain signal for LBs. A
        co-resident training job's guardrails (watchdog stall, NaN storm)
        degrade this process too: a host whose device is wedged or whose
        numerics are melting should not take serving traffic either."""
        if self._draining:
            return {"status": "draining"}
        if self.breaker is not None:
            snap = self.breaker.snapshot()
            if snap["state"] != "closed":
                return {"status": "degraded", "breaker": snap}
        g = _guardrails.health()
        if g["status"] != "ok":
            return {"status": "degraded", "guardrails": g}
        e = _elastic.health()
        if e["status"] != "ok":
            # a pending eviction notice or lost peers: drain THIS instance
            # too — traffic routed to a host mid-eviction is wasted work
            return {"status": "degraded", "elastic": e}
        return {"status": "ok"}

    @property
    def address(self):
        """(host, port) actually bound — resolves port=0."""
        return self._httpd.server_address[:2]

    @property
    def url(self):
        host, port = self.address
        return "http://%s:%d" % (host, port)

    def start(self):
        """Serve in a background thread; returns self (chainable)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="model-server")
            self._thread.start()
        return self

    def serve(self):
        """Blocking serve (Ctrl-C to stop)."""
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self, drain=True, timeout=10.0):
        """Graceful shutdown, bounded by ``timeout`` seconds.

        Order matters: first flip :attr:`draining` so new POSTs are shed
        with 503 (instead of racing the socket close), then drain the
        batcher — in-flight requests complete and their HTTP responses go
        out over the still-open listener — and only then stop the
        listener. ``drain=False`` fails queued work immediately with
        ``ServerClosed``."""
        self._draining = True
        self.batcher.close(drain=drain, timeout=timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self.metrics.unbind_profiler()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
