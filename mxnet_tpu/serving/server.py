"""ModelServer: stdlib HTTP front-end for the serving stack.

Role parity: MXNet Model Server's REST surface (``/predictions``,
``/ping``, ``/metrics``), reduced to the stdlib so the whole serving path —
HTTP → DynamicBatcher → InferenceEngine → XLA — is exercisable end-to-end
with zero extra dependencies. ``ThreadingHTTPServer`` gives one thread per
in-flight request, which is exactly the concurrency shape the batcher
coalesces.

Endpoints (JSON):

- ``POST /predict`` — body ``{"data": [...]}`` (one sample, no batch
  axis) or ``{"inputs": [[...], ...]}`` for multi-input models; optional
  ``"dtype"`` (default float32) and ``"timeout_ms"``. Response
  ``{"output": [...]}`` (or ``{"outputs": [...]}``). Typed failures map
  to load-balancer-friendly codes: ServerBusy→503, DeadlineExceeded→504,
  malformed input→400, body over ``MXNET_HTTP_MAX_BODY``→413 (consumed
  first, so keep-alive stays in sync). With a fleet ``registry=``,
  ``/predict/<model>`` (or a ``"model"`` body field) routes to that
  model's serving/canary version and the response carries
  ``X-Model-Version``.
- ``POST /generate`` — autoregressive generation (requires a
  ``generator=`` :class:`~.generation.GenerationScheduler`): body
  ``{"prompt": [token ids], "max_new_tokens": n, "temperature": t,
  "eos_id": id, "stream": true}``. With ``stream`` (default) the reply is
  ``Transfer-Encoding: chunked`` NDJSON, one ``{"token", "index"}`` line
  per generated token as the continuous-batching loop produces it, closed
  by a ``{"done": true, "reason": ...}`` line — time-to-first-token is
  one prefill away regardless of how many other sequences are mid-flight.
- ``GET /healthz`` — liveness + degradation: ``{"status": "ok"}`` in
  normal service, ``"degraded"`` (with breaker state) while the circuit
  breaker is open/half-open, ``"draining"`` during shutdown — load
  balancers key off the status field to drain the instance.
- ``GET /metrics`` — ``ServingMetrics.snapshot()`` (QPS, latency
  percentiles, occupancy, queue depth, executor-cache counters, retry
  counters, breaker state).
- ``GET /metrics.prom`` (also ``/metrics?format=prometheus``) — the same
  sources plus the telemetry plane (device HBM, MFU, trace histograms
  with kept-trace exemplars) in Prometheus text exposition format,
  ``mxtpu_*``-named for a standard scrape (see docs/observability.md).

Resilience: model failures feed a
:class:`~mxnet_tpu.resilience.breaker.CircuitBreaker`; while it is open,
``/predict`` fast-fails with 503 + ``Retry-After`` instead of queueing
doomed work, then half-open probes let real traffic close it again.

Tracing: every ``/predict`` gets an ``X-Request-Id`` (honored from the
incoming header, minted otherwise) echoed on the response, and — while
``mxnet_tpu.observability`` tracing is on — a ``serving.http`` root span
carrying it. The request's queue wait, batch assembly, and engine
execution are recorded as linked spans (same trace id) even though they
run on the batcher worker thread, so a p99 outlier in ``profiler.dump()``
decomposes into its phases instead of being one opaque latency number.
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from .. import config as _config
from ..observability import attribution as _attr
from ..observability import telemetry as _telemetry
from ..observability import tracer as _trace
from ..resilience import elastic as _elastic
from ..resilience import guardrails as _guardrails
from ..resilience import retry as _retry
from ..resilience.breaker import CircuitBreaker, CircuitOpen
from .batcher import (DeadlineExceeded, DynamicBatcher, ServerBusy,
                      ServerClosed, ServingError)
from .engine import InferenceEngine
from .fleet import ModelNotFound, StaleVersion, VersionNotFound
from .metrics import ServingMetrics

__all__ = ["ModelServer"]


def read_post_body(handler):
    """Read a POST body off ``handler`` (any handler with ``_reply``)
    with HTTP/1.1 keep-alive discipline — shared by ``ModelServer`` and
    the gateway so the body rules can't drift apart:

    - consume the body FIRST: an early reply with the body still unread
      desyncs keep-alive (the next request on the connection would be
      parsed starting at the leftover body bytes);
    - the client-declared Content-Length is untrusted: never buffer more
      than ``MXNET_HTTP_MAX_BODY`` — still CONSUME an oversized body (in
      bounded chunks) before the 413 so the connection stays in sync.

    Returns the body bytes, or None after having replied on failure."""
    try:
        length = int(handler.headers.get("Content-Length", 0))
        if length < 0:  # read(-1) would block until client EOF
            raise ValueError("negative Content-Length")
    except (ValueError, TypeError):
        handler.close_connection = True  # unknown length: can't resync
        handler._reply(400, {"error": "bad Content-Length"})
        return None
    max_body = _config.get("MXNET_HTTP_MAX_BODY")
    if max_body > 0 and length > max_body:
        remaining = length
        while remaining > 0:
            chunk = handler.rfile.read(min(remaining, 1 << 16))
            if not chunk:  # client gave up mid-body: can't resync
                handler.close_connection = True
                break
            remaining -= len(chunk)
        handler._reply(413, {"error": "request body %d bytes exceeds "
                                      "MXNET_HTTP_MAX_BODY=%d"
                                      % (length, max_body)})
        return None
    return handler.rfile.read(length)


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet_tpu_serving/0.1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: metrics replace access logs
        pass

    def _reply(self, code, payload, headers=None):
        self._last_code = code
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        # failure replies mark the request's root span so the tail
        # sampler keeps the whole trace: 5xx = fault, 504 = deadline —
        # the spans a bad p99 bucket's exemplar must link to
        span = getattr(self, "_http_span", None)
        if span is not None and code >= 500:
            span.set(error=code)

    def _reply_text(self, code, body, content_type):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (http.server API)
        # a keep-alive connection reuses this handler across requests: a
        # GET after a POST must not echo the POST's stale request id
        self._request_id = None
        self._http_span = None
        srv = self.server.model_server
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._reply(200, srv.health())
        elif path == "/drain":
            # admin-only: flips /healthz to "draining" so a fronting
            # gateway stops routing here BEFORE the supervisor sends
            # SIGTERM — the first half of a zero-drop rolling restart
            if not self._admin_ok():
                self._reply(403, {"error": "admin endpoint: missing or "
                                           "bad X-Admin-Token"})
                return
            srv.begin_drain()
            self._reply(202, {"status": "draining"})
        elif path == "/metrics.prom" or (
                path == "/metrics" and "format=prometheus" in query):
            from ..observability import export_prom as _prom
            self._reply_text(200, _prom.render_server(srv),
                             _prom.CONTENT_TYPE)
        elif path == "/metrics":
            self._reply(200, srv.metrics.snapshot())
        else:
            self._reply(404, {"error": "unknown path %s" % self.path})

    def _admin_ok(self):
        """Admin-endpoint guard: when ``MXNET_SERVING_ADMIN_TOKEN`` is
        set, the request must carry it in ``X-Admin-Token``; empty token
        leaves the endpoint open (dev/test topologies where the gateway
        and replicas share a trust boundary)."""
        token = _config.get("MXNET_SERVING_ADMIN_TOKEN")
        if not token:
            return True
        return self.headers.get("X-Admin-Token") == token

    def do_POST(self):  # noqa: N802
        # the request id propagates: honored from the client's header
        # (upstream tracing), minted otherwise; echoed on every reply and
        # attached to the request's whole span chain
        rid = self.headers.get("X-Request-Id") or uuid.uuid4().hex[:16]
        self._request_id = rid
        self._last_code = None
        t0 = time.perf_counter()
        with _trace.span("serving.http", request_id=rid,
                         path=self.path) as sp:
            self._http_span = sp
            try:
                self._handle_post(rid)
            finally:
                self._http_span = None
        # the flight recorder's request timeline rides regardless of
        # whether a trace session is running — that is its whole point
        _attr.flight_note("request", request_id=rid,
                          path=self.path.partition("?")[0],
                          status=self._last_code,
                          wall_ms=(time.perf_counter() - t0) * 1e3)

    # ---- on-demand production profiling -----------------------------------
    def _handle_profile_capture(self, query, body):
        """``POST /debug/profile?seconds=N`` (admin-guarded): capture N
        seconds of live traffic — host spans, flight ring, roofline
        attribution, and the jax/XPlane device trace when available —
        into a checksummed artifact dir, replying with its manifest.
        The capture runs on THIS handler thread; every other thread
        keeps serving, which is the point: chip-side investigation
        without a redeploy. 409 while another capture runs."""
        import urllib.parse
        if not self._admin_ok():
            self._reply(403, {"error": "admin endpoint: missing or bad "
                                       "X-Admin-Token"})
            return
        params = urllib.parse.parse_qs(query)
        try:
            payload = json.loads(body or b"{}") or {}
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            seconds = float(params.get("seconds", [None])[0]
                            or payload.get("seconds", 1.0))
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            # the artifact dir is always capture_profile's own
            # MXNET_PROF_DIR-derived path: accepting a client-chosen
            # directory here would hand the wire an arbitrary-path
            # file-write primitive (worse through the gateway proxy)
            manifest = _attr.capture_profile(seconds)
        except _attr.CaptureBusy as e:
            self._reply(409, {"error": str(e)},
                        headers={"Retry-After": "1"})
            return
        except OSError as e:
            self._reply(500, {"error": "capture failed: %s: %s"
                              % (type(e).__name__, e)})
            return
        self._reply(200, manifest)

    def _handle_flight_dump(self):
        """``POST /debug/flight`` (admin-guarded): dump the flight ring
        now — the HTTP twin of ``kill -USR2`` for operators without
        shell access to the host."""
        if not self._admin_ok():
            self._reply(403, {"error": "admin endpoint: missing or bad "
                                       "X-Admin-Token"})
            return
        path = _attr.flight_dump("http_request")
        if path is None:
            self._reply(503, {"error": "flight recorder disabled or "
                                       "dump unwritable"})
            return
        self._reply(200, {"path": path,
                          "records": len(_attr.flight.records())})

    @staticmethod
    def _split_model_path(path):
        """``/predict`` → ``("/predict", None)``; ``/predict/resnet`` →
        ``("/predict", "resnet")`` (same for ``/generate``) — the fleet's
        path-segment routing. Unrecognized paths pass through as-is."""
        for base in ("/predict", "/generate"):
            if path == base:
                return base, None
            if path.startswith(base + "/"):
                return base, path[len(base) + 1:] or None
        return path, None

    def _handle_post(self, rid):
        srv = self.server.model_server
        body = read_post_body(self)
        if body is None:
            return
        raw_path, _, query = self.path.partition("?")
        if raw_path == "/debug/profile":
            self._handle_profile_capture(query, body)
            return
        if raw_path == "/debug/flight":
            self._handle_flight_dump()
            return
        path, model_name = self._split_model_path(raw_path)
        if path == "/generate":
            self._handle_generate(rid, srv, body, model_name)
            return
        if path != "/predict":
            self._reply(404, {"error": "unknown path %s" % self.path})
            return
        if srv.registry is not None:
            self._handle_fleet_predict(rid, srv, body, model_name)
            return
        if model_name is not None:
            self._reply(404, {"error": "no model registry configured "
                                       "(single-model server)"})
            return
        if srv.batcher is None:
            self._reply(404, {"error": "no predict model loaded"})
            return
        if srv.draining:
            # shutdown in progress: shed new work BEFORE the socket goes
            # away so clients get a clean 503, not a connection reset
            self._reply(503, {"error": "server draining"},
                        headers={"Retry-After": "1"})
            return
        # parse BEFORE breaker admission: a malformed body (400) must
        # never hold a half-open probe slot
        try:
            payload = json.loads(body or b"{}")
            if "inputs" in payload:
                raw = payload["inputs"]
            elif "data" in payload:
                raw = [payload["data"]]
            else:
                raise ValueError('body needs "data" or "inputs"')
            dtype = payload.get("dtype", "float32")
            inputs = [_np.asarray(x, dtype=dtype) for x in raw]
            timeout_ms = payload.get("timeout_ms")
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        breaker = srv.breaker
        admission = breaker.allow() if breaker is not None else True
        if not admission:
            retry_after = max(1, int(round(breaker.retry_after_s())))
            snap = breaker.snapshot()
            self._reply(503, {"error": "circuit open: %s" % snap["state"],
                              "breaker": snap},
                        headers={"Retry-After": str(retry_after)})
            return
        try:
            row = srv.batcher.predict(*inputs, timeout_ms=timeout_ms,
                                      request_id=rid)
        except ServerBusy as e:
            # backpressure, not a model fault: the breaker must not trip
            if breaker is not None:
                breaker.release(admission)
            self._reply(503, {"error": str(e)},
                        headers={"Retry-After": "1"})
            return
        except DeadlineExceeded as e:
            if breaker is not None:
                breaker.release(admission)
            self._reply(504, {"error": str(e)})
            return
        except ServerClosed as e:
            if breaker is not None:
                breaker.release(admission)
            self._reply(503, {"error": str(e)},
                        headers={"Retry-After": "1"})
            return
        except Exception as e:  # noqa: BLE001 — model failure
            if breaker is not None:
                breaker.record_failure(admission)
            self._reply(500, {"error": "%s: %s" % (type(e).__name__, e)})
            return
        if breaker is not None:
            breaker.record_success(admission)
        if isinstance(row, tuple):
            self._reply(200, {"outputs": [_np.asarray(r).tolist()
                                          for r in row]})
        else:
            self._reply(200, {"output": _np.asarray(row).tolist()})

    # ---- fleet routing ----------------------------------------------------
    def _handle_fleet_predict(self, rid, srv, body, model_name):
        """``/predict`` against a :class:`~.fleet.ModelRegistry`: resolve
        the model (path segment beats body ``"model"`` field; ``None``
        routes to the default model for wire back-compat), run the
        request through that model's bulkhead lane, and echo
        ``X-Model-Version`` so every response attributes the exact
        version that produced it."""
        if srv.draining:
            self._reply(503, {"error": "server draining"},
                        headers={"Retry-After": "1"})
            return
        try:
            payload = json.loads(body or b"{}")
            if model_name is None:
                model_name = payload.get("model") or None
            if "inputs" in payload:
                raw = payload["inputs"]
            elif "data" in payload:
                raw = [payload["data"]]
            else:
                raise ValueError('body needs "data" or "inputs"')
            dtype = payload.get("dtype", "float32")
            inputs = [_np.asarray(x, dtype=dtype) for x in raw]
            timeout_ms = payload.get("timeout_ms")
        except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return

        def _ver_headers(exc=None, mv=None, extra=None):
            mv = mv or getattr(exc, "model_version", None)
            out = dict(extra or {})
            if mv is not None:
                out["X-Model-Version"] = mv.label
            return out

        try:
            row, mv = srv.registry.predict(
                *inputs, model=model_name, timeout_ms=timeout_ms,
                request_id=rid)
        except (ModelNotFound, VersionNotFound) as e:
            self._reply(404, {"error": str(e)})
            return
        except CircuitOpen as e:
            # the LANE's breaker — one bad model fast-fails its own
            # traffic while every other model keeps serving
            retry_after = max(1, int(round(e.retry_after_s)))
            self._reply(503, {"error": str(e)},
                        headers=_ver_headers(
                            e, extra={"Retry-After": str(retry_after)}))
            return
        except (ServerBusy, ServerClosed) as e:
            self._reply(503, {"error": str(e)},
                        headers=_ver_headers(
                            e, extra={"Retry-After": "1"}))
            return
        except DeadlineExceeded as e:
            self._reply(504, {"error": str(e)}, headers=_ver_headers(e))
            return
        except Exception as e:  # noqa: BLE001 — model failure (this lane)
            self._reply(500, {"error": "%s: %s" % (type(e).__name__, e)},
                        headers=_ver_headers(e))
            return
        headers = _ver_headers(mv=mv)
        if isinstance(row, tuple):
            self._reply(200, {"outputs": [_np.asarray(r).tolist()
                                          for r in row]}, headers=headers)
        else:
            self._reply(200, {"output": _np.asarray(row).tolist()},
                        headers=headers)

    # ---- generation (streamed tokens) -------------------------------------
    def _write_chunk(self, payload):
        """One HTTP/1.1 chunk carrying one NDJSON line."""
        data = (json.dumps(payload) + "\n").encode("utf-8")
        self.wfile.write(b"%x\r\n" % len(data))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()

    def _handle_generate(self, rid, srv, body, model_name=None):
        """``POST /generate``: continuous-batched autoregressive decoding
        with tokens streamed back as they are produced.

        Body: ``{"prompt": [id, ...]}`` plus optional ``max_new_tokens``,
        ``temperature`` (0 = greedy), ``eos_id``, ``timeout_ms`` (queue
        deadline) and ``stream`` (default true). Streaming responses are
        ``Transfer-Encoding: chunked`` NDJSON — one ``{"token": id,
        "index": i}`` line per token, then a ``{"done": true, ...}``
        summary line; ``stream=false`` collects everything into one
        ``{"tokens": [...], "reason": ...}`` JSON reply. Typed failures
        map exactly like ``/predict`` (busy→503, queue deadline→504,
        malformed/oversized prompt→400); a fault mid-stream becomes an
        ``{"error": ...}`` line and the connection closes.

        With a fleet registry, ``/generate/<model>`` (or a ``"model"``
        body field) routes to that model's serving/canary version; the
        request holds the version's lease for the WHOLE stream, so a
        hot-swap drains behind in-flight generations instead of cutting
        them off, and replies carry ``X-Model-Version``."""
        if srv.draining:
            self._reply(503, {"error": "server draining"},
                        headers={"Retry-After": "1"})
            return
        # parse ONCE — the fleet's model-field routing and the request
        # fields below share this dict (bodies run up to
        # MXNET_HTTP_MAX_BODY; re-parsing long prompts would double the
        # hot path's parse cost)
        try:
            payload = json.loads(body or b"{}")
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return
        mv = None
        lease = None
        if srv.registry is not None:
            try:
                payload_model = model_name
                if payload_model is None and isinstance(payload, dict):
                    payload_model = payload.get("model") or None
                for _ in range(8):
                    mv = srv.registry.route(payload_model, rid)
                    try:
                        lease = mv.lease()
                        lease.__enter__()
                        break
                    except StaleVersion:
                        lease = None
                else:
                    self._reply(503, {"error": "model kept draining"},
                                headers={"Retry-After": "1"})
                    return
            except (ModelNotFound, VersionNotFound) as e:
                self._reply(404, {"error": str(e)})
                return
            try:
                self._generate_on(rid, srv, payload, mv.generator,
                                  mv.breaker, mv)
            finally:
                lease.__exit__(None, None, None)
            return
        if model_name is not None:
            self._reply(404, {"error": "no model registry configured "
                                       "(single-model server)"})
            return
        self._generate_on(rid, srv, payload, srv.generator, srv.breaker,
                          None)

    def _generate_on(self, rid, srv, payload, generator, breaker, mv):
        """Run one ``/generate`` against a resolved (generator, breaker)
        lane; ``mv`` (fleet mode) adds ``X-Model-Version`` attribution
        and feeds the lane's outcome window (what the canary controller
        watches)."""
        extra = {} if mv is None else {"X-Model-Version": mv.label}
        if generator is None:
            self._reply(404, {"error": "no generation model loaded"
                              if mv is None else
                              "%s has no generation lane" % mv.label},
                        headers=extra)
            return
        t_start = time.monotonic()

        def _outcome(ok):
            if mv is not None:
                mv.record_outcome(ok, time.monotonic() - t_start)

        try:
            prompt = payload["prompt"]
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int) for t in prompt)):
                raise ValueError('"prompt" must be a non-empty list of '
                                 'token ids')
            # every optional field is coerced HERE so a bad type is a 400,
            # never an exception escaping into the socket layer
            max_new = payload.get("max_new_tokens")
            max_new = None if max_new is None else int(max_new)
            temperature = float(payload.get("temperature", 0.0))
            eos_id = payload.get("eos_id")
            eos_id = None if eos_id is None else int(eos_id)
            timeout_ms = payload.get("timeout_ms")
            timeout_ms = None if timeout_ms is None else float(timeout_ms)
            stream = bool(payload.get("stream", True))
        except (ValueError, TypeError, KeyError,
                json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)}, headers=extra)
            return
        admission = breaker.allow() if breaker is not None else True
        if not admission:
            retry_after = max(1, int(round(breaker.retry_after_s())))
            snap = breaker.snapshot()
            self._reply(503, {"error": "circuit open: %s" % snap["state"],
                              "breaker": snap},
                        headers={**extra,
                                 "Retry-After": str(retry_after)})
            return
        try:
            if mv is not None:
                # canary generation traffic passes the same fleet.rollout
                # chaos point as predict: injected faults surface as lane
                # failures below and feed the controller's window
                mv.rollout_gate()
            req = generator.submit(
                prompt, max_new_tokens=max_new, temperature=temperature,
                eos_id=eos_id, timeout_ms=timeout_ms, request_id=rid)
        except ServerBusy as e:
            if breaker is not None:
                breaker.release(admission)
            self._reply(503, {"error": str(e)},
                        headers={**extra, "Retry-After": "1"})
            return
        except ServerClosed as e:
            if breaker is not None:
                breaker.release(admission)
            self._reply(503, {"error": str(e)},
                        headers={**extra, "Retry-After": "1"})
            return
        except ServingError as e:  # PromptTooLong / bad request shape
            if breaker is not None:
                breaker.release(admission)
            self._reply(400, {"error": str(e)}, headers=extra)
            return
        except Exception as e:  # noqa: BLE001 — injected/submit-time fault
            if breaker is not None:
                breaker.record_failure(admission)
            _outcome(False)
            self._reply(500, {"error": "%s: %s" % (type(e).__name__, e)},
                        headers=extra)
            return
        if not stream:
            try:
                toks = req.result()
            except DeadlineExceeded as e:  # expired in queue: not a fault
                if breaker is not None:
                    breaker.release(admission)
                self._reply(504, {"error": str(e)}, headers=extra)
                return
            except ServerClosed as e:
                if breaker is not None:
                    breaker.release(admission)
                self._reply(503, {"error": str(e)},
                            headers={**extra, "Retry-After": "1"})
                return
            except Exception as e:  # noqa: BLE001 — model fault
                if breaker is not None:
                    breaker.record_failure(admission)
                _outcome(False)
                self._reply(500, {"error": "%s: %s"
                                  % (type(e).__name__, e)}, headers=extra)
                return
            if breaker is not None:
                breaker.record_success(admission)
            _outcome(True)
            self._reply(200, {"tokens": toks, "reason": req.finish_reason},
                        headers=extra)
            return
        # streamed: hold the status line until the FIRST event so
        # pre-first-token failures (queue deadline, drain, prefill fault)
        # keep their typed HTTP codes exactly like the non-streamed path;
        # only once a token exists do we commit to 200 + chunked, after
        # which failures ride in-band as an "error" line
        kind, val = req.next_event()
        if kind == "error":
            if isinstance(val, DeadlineExceeded):
                if breaker is not None:
                    breaker.release(admission)
                self._reply(504, {"error": str(val)}, headers=extra)
            elif isinstance(val, (ServerBusy, ServerClosed)):
                if breaker is not None:
                    breaker.release(admission)
                self._reply(503, {"error": str(val)},
                            headers={**extra, "Retry-After": "1"})
            else:
                if breaker is not None:
                    breaker.record_failure(admission)
                _outcome(False)
                self._reply(500, {"error": "%s: %s"
                                  % (type(val).__name__, val)},
                            headers=extra)
            return
        self.send_response(200)
        # committed to the stream: record the status for the flight
        # recorder's request record (_reply never runs on this path)
        self._last_code = 200
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("X-Request-Id", rid)
        for k, v in extra.items():
            self.send_header(k, v)
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            if kind == "token":
                self._write_chunk({"token": val, "index": 0})
                for i, tok in enumerate(req.tokens(), start=1):
                    self._write_chunk({"token": tok, "index": i})
            self._write_chunk({"done": True, "request_id": rid,
                               "n_tokens": len(req.tokens_out),
                               "reason": req.finish_reason})
            self.wfile.write(b"0\r\n\r\n")
            if breaker is not None:
                breaker.record_success(admission)
            _outcome(True)
        except Exception as e:  # noqa: BLE001 — fault mid-stream
            # the consumer is gone or broken either way: retire the
            # sequence at the next iteration instead of decoding the rest
            # of its budget into an unread queue
            req.cancel()
            if isinstance(e, (DeadlineExceeded, ServerClosed, OSError)):
                # queue expiry / drain / client went away: not a model
                # fault — the breaker must not trip
                if breaker is not None:
                    breaker.release(admission)
            else:
                if breaker is not None:
                    breaker.record_failure(admission)
                _outcome(False)
            try:
                self._write_chunk({"error": "%s: %s"
                                   % (type(e).__name__, e)})
                self.wfile.write(b"0\r\n\r\n")
            except Exception:
                pass
            self.close_connection = True


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that doesn't traceback-spam stderr when a
    client disconnects mid-reply (timed-out health probe, closed
    browser) — routine under load balancers, not a server fault."""

    def handle_error(self, request, client_address):
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return
        super().handle_error(request, client_address)


class ModelServer:
    """Wire engine + batcher + metrics + breaker behind one HTTP listener.

    ``model`` may be an :class:`InferenceEngine` (pre-configured buckets /
    warmup) or any batched callable, in which case an engine is built with
    ``buckets``; ``None`` serves generation only. ``port=0`` picks an
    ephemeral port (tests). ``generator`` is a
    :class:`~.generation.GenerationScheduler` backing ``POST /generate``
    (closed with the server; its ``GenerationMetrics``, when present,
    become the ``/metrics`` ``"generation"`` section).

    ``breaker=None`` (default) builds a :class:`CircuitBreaker` from the
    ``MXNET_BREAKER_*`` env knobs (set ``MXNET_BREAKER_FAILURE_THRESHOLD``
    <= 0 to disable); pass a configured breaker, or ``False`` to disable
    explicitly. ``retry_policy`` is forwarded to the batcher — the single
    retry layer in this stack; an engine built here gets
    ``retry_policy=False`` (pass a pre-built engine to layer differently).

    ``registry`` (exclusive with ``model``/``generator``) serves a
    :class:`~.fleet.ModelRegistry` fleet instead: ``/predict`` and
    ``/generate`` route by model name (path segment or body field,
    default-model back-compat), each model×version runs in its own
    bulkhead lane with its own breaker, responses echo
    ``X-Model-Version``, and ``/healthz`` + ``/metrics`` grow per-model
    sections. The registry's lanes are drained and closed by
    :meth:`stop`.

    ``artifacts_dir`` kills the restart compile storm (ROADMAP item 4):
    AOT executables exported by ``InferenceEngine.export_artifacts`` /
    ``tools/prewarm.py`` are installed into the engine at construction
    (zero XLA compiles for every covered bucket; a fingerprint mismatch
    or corrupt artifact warns once and compiles normally — a bad
    artifact must never keep a server down), and the directory's
    ``warmup.json`` traffic manifest is replayed on a **background**
    thread in traffic-frequency order, so the server accepts requests
    immediately while the hottest rungs warm first. Progress rides
    ``/metrics`` under the ``"coldstart"`` gauge.
    """

    def __init__(self, model=None, host="127.0.0.1", port=8080,
                 buckets=None, jit=True, max_batch_size=32,
                 max_latency_ms=5.0, max_queue_size=128,
                 default_timeout_ms=None, metrics=None,
                 breaker=None, retry_policy=None,
                 bind_profiler=True, generator=None, registry=None,
                 artifacts_dir=None):
        self.metrics = metrics or ServingMetrics()
        self.generator = generator
        self.registry = registry
        if registry is not None:
            # fleet mode: every lane owns its own engine/batcher/breaker;
            # the server is pure routing + the process-level gauges
            if model is not None or generator is not None:
                raise ValueError("pass EITHER registry= OR "
                                 "model/generator, not both")
            if breaker is not None:
                raise ValueError(
                    "registry= servers take no server-level breaker: "
                    "each lane owns its own (pass breaker= to "
                    "ModelRegistry.load)")
            self.engine = None
        elif model is None:
            # generation-only server: no /predict path
            if generator is None:
                raise ValueError(
                    "need a model, a generator, or a registry")
            self.engine = None
        elif isinstance(model, InferenceEngine):
            self.engine = model
            self.metrics.set_cache_stats_fn(self.engine.stats)
        else:
            from .engine import DEFAULT_BUCKETS
            # retry lives at the batcher layer here (it re-runs the whole
            # coalesced batch); a second engine-level policy underneath
            # would only multiply attempts and split the counters
            self.engine = InferenceEngine(
                model, buckets=buckets or DEFAULT_BUCKETS, jit=jit,
                metrics=self.metrics, retry_policy=False)
        if registry is not None:
            breaker = False   # rejected above unless None: lanes own theirs
        elif breaker is None:
            threshold = _config.get("MXNET_BREAKER_FAILURE_THRESHOLD")
            breaker = CircuitBreaker(
                failure_threshold=threshold,
                recovery_ms=_config.get("MXNET_BREAKER_RECOVERY_MS"),
                half_open_probes=_config.get(
                    "MXNET_BREAKER_HALF_OPEN_PROBES"),
                name="serving") if threshold > 0 else False
        self.breaker = breaker or None
        if self.breaker is not None:
            self.metrics.set_gauge_fn("breaker", self.breaker.snapshot)
        if registry is not None:
            # per-model × version sections on /metrics, plus the fleet's
            # pointer/rollback ledger
            self.metrics.set_gauge_fn("models", registry.metrics_snapshot)
            self.metrics.set_gauge_fn("fleet", registry.stats)
        self.metrics.set_gauge_fn("retry", _retry.all_stats)
        self.metrics.set_gauge_fn("guardrails", _guardrails.all_stats)
        # elastic membership: the LB-visible view of "how many hosts does
        # this job still have" plus pending-preemption state
        self.metrics.set_gauge_fn("elastic", _elastic.membership_gauge)
        from ..parallel import datafeed as _datafeed
        self.metrics.set_gauge_fn("datafeed", _datafeed.feed_stats)
        # trace-derived per-phase latency histograms on /metrics: the
        # timeline's aggregate view without parsing the dumped JSON
        self.metrics.set_gauge_fn("trace", _trace.summary_gauge)
        # device HBM / FLOPs / MFU: the same numbers /metrics.prom
        # exposes, on the JSON surface
        self.metrics.set_gauge_fn("telemetry", _telemetry.telemetry_gauge)
        # per-executable roofline attribution (the ranked kernel-work
        # target list) on the JSON surface too
        self.metrics.set_gauge_fn("roofline", _attr.roofline_gauge)
        # post-mortem readiness: a serving process answers `kill -USR2`
        # with a flight dump (no-op when called off the main thread —
        # the embedding process then owns the disposition)
        if _attr.flight_enabled():
            _attr.install_flight_signal_handler()
        # generation lane: slot-arena occupancy + scheduler state, plus
        # this server's TTFT / tokens-per-slot percentiles when a
        # generator with GenerationMetrics is attached
        from . import generation as _generation
        if self.generator is not None and \
                getattr(self.generator, "metrics", None) is not None:
            gen_metrics = self.generator.metrics
            self.metrics.set_gauge_fn("generation", gen_metrics.snapshot)
            if bind_profiler:
                gen_metrics.bind_profiler()
        else:
            self.metrics.set_gauge_fn("generation", _generation.gauge)
        # sharded lane: mesh identity (axis names+sizes, chips, plan) as
        # a /metrics gauge — what the gateway scrape reads to know this
        # replica is "a planned mesh of M chips", not one chip
        mesh_src = getattr(self.generator, "engine", None) \
            if self.generator is not None else None
        mesh_fn = getattr(mesh_src or self.engine, "mesh_info", None)
        if mesh_fn is not None:
            self.metrics.set_gauge_fn("mesh", mesh_fn)
        # cold-start ledger: persistent-cache hits, AOT loads/fallbacks,
        # and the live prewarm replay's progress — restart health at a
        # glance without a Prometheus scrape
        from .. import pcache as _pcache
        engine_ref = self.engine
        self.metrics.set_gauge_fn(
            "coldstart",
            lambda: {"pcache": _pcache.stats(),
                     "prewarm": (engine_ref.prewarm_status()
                                 if engine_ref is not None else None)})
        if bind_profiler:
            self.metrics.bind_profiler()
        if artifacts_dir is not None:
            if self.engine is None:
                raise ValueError("artifacts_dir= needs a /predict engine")
            self._load_artifacts(artifacts_dir)
        self._draining = False
        self._stop_started = False
        self.batcher = None if self.engine is None else DynamicBatcher(
            self.engine, max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms, max_queue_size=max_queue_size,
            default_timeout_ms=default_timeout_ms, metrics=self.metrics,
            retry_policy=retry_policy)
        self._httpd = _QuietThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.model_server = self
        self._thread = None

    def _load_artifacts(self, artifacts_dir):
        """Install AOT executables and kick off the background prewarm
        replay. Every failure mode short of a programming error degrades
        to normal compiles with a warn-once — a stale or corrupt
        artifact must never keep a restarted server from coming up."""
        import os

        from .. import aot as _aot
        from .. import pcache as _pcache
        artifact = os.path.join(artifacts_dir, _aot.ARTIFACT_NAME)
        if os.path.exists(artifact):
            try:
                self.engine.load_artifacts(artifacts_dir)
            except _aot.ArtifactError as exc:
                _pcache.note_aot_fallback(str(exc), where="ModelServer")
        else:
            _pcache.note_aot_fallback("no %s under %s"
                                      % (_aot.ARTIFACT_NAME, artifacts_dir),
                                      where="ModelServer")
        warmup = os.path.join(artifacts_dir, _aot.WARMUP_NAME)
        if os.path.exists(warmup):
            try:
                self.engine.prewarm(manifest=warmup, background=True)
            except (ValueError, OSError) as exc:
                _pcache.note_aot_fallback("warmup manifest unusable: %s"
                                          % exc, where="ModelServer")

    @property
    def draining(self):
        return self._draining

    def begin_drain(self):
        """Flip this server to ``draining``: ``/healthz`` reports it (so
        gateways/LBs stop routing here), new POSTs are shed with 503,
        and in-flight work keeps completing. The listener stays up —
        pair with :meth:`stop` (or the SIGTERM handler) to actually shut
        down once traffic has moved away."""
        self._draining = True

    def install_drain_handler(self, signals=None, grace_ms=None,
                              on_stopped=None):
        """Wire the bounded-drain :meth:`stop` to process signals so a
        supervised replica killed by its supervisor (rolling restart,
        autoscale shrink, preemption) always drains instead of dropping
        in-flight requests.

        Same grace-window semantics as
        :class:`~mxnet_tpu.resilience.elastic.PreemptionHandler`:
        ``grace_ms`` (default ``MXNET_ELASTIC_GRACE_MS``) bounds how long
        the drain may take — the supervisor's SIGKILL follow-up must
        never land while waiters are still blocked. The handler flips
        :attr:`draining` immediately (``/healthz`` degrades before any
        slow teardown), then runs ``stop(drain=True)`` on a background
        thread and finally calls ``on_stopped()`` (e.g. ``sys.exit``).

        Signal dispositions are process-global: install from the main
        thread only, one server per process. Returns self. Idempotent
        per server; repeated signals don't restart the drain."""
        import signal as _signal
        if grace_ms is None:
            grace_ms = _config.get("MXNET_ELASTIC_GRACE_MS")
        self._drain_grace_s = float(grace_ms) / 1e3
        self._drain_on_stopped = on_stopped
        for s in (signals if signals is not None else (_signal.SIGTERM,)):
            _signal.signal(s, self._on_drain_signal)
        return self

    def _on_drain_signal(self, signum, frame):
        # async-signal path: flag writes + one thread spawn only.
        # Keyed on _stop_started, NOT on draining: a replica that was
        # told to /drain first (the rolling-restart order) must still
        # honor the SIGTERM that follows
        if getattr(self, "_stop_started", False):
            return  # stop already under way; don't restart it
        self._stop_started = True
        self._draining = True
        t = threading.Thread(target=self._drain_and_stop,
                             name="model-server-drain", daemon=True)
        t.start()

    def _drain_and_stop(self):
        # leave a margin inside the grace window: the drain must finish
        # (and stragglers be failed with typed ServerClosed) before the
        # supervisor's SIGKILL follow-up can land
        timeout = max(0.1, getattr(self, "_drain_grace_s", 10.0) * 0.8)
        try:
            self.stop(drain=True, timeout=timeout)
        finally:
            cb = getattr(self, "_drain_on_stopped", None)
            if cb is not None:
                cb()

    def prometheus_text(self):
        """The ``GET /metrics.prom`` body (Prometheus text format):
        every stats source this process holds — serving/generation/fleet
        lanes plus the process-wide telemetry plane."""
        from ..observability import export_prom as _prom
        return _prom.render_server(self)

    def health(self):
        """The ``/healthz`` payload: ``ok`` | ``degraded`` | ``draining``
        (+ breaker state when degraded) — the drain signal for LBs. A
        co-resident training job's guardrails (watchdog stall, NaN storm)
        degrade this process too: a host whose device is wedged or whose
        numerics are melting should not take serving traffic either."""
        if self._draining:
            return {"status": "draining"}
        if self.breaker is not None:
            snap = self.breaker.snapshot()
            if snap["state"] != "closed":
                return {"status": "degraded", "breaker": snap}
        g = _guardrails.health()
        if g["status"] != "ok":
            return {"status": "degraded", "guardrails": g}
        m = _telemetry.memory_health()
        if m["status"] != "ok":
            # HBM headroom below the floor: degrade BEFORE the OOM, while
            # the LB can still drain this host instead of burying it
            return {"status": "degraded", "memory": m}
        e = _elastic.health()
        if e["status"] != "ok":
            # a pending eviction notice or lost peers: drain THIS instance
            # too — traffic routed to a host mid-eviction is wasted work
            return {"status": "degraded", "elastic": e}
        if self.registry is not None:
            # per-model lanes: one degraded model degrades ITS section
            # only (bulkhead semantics — the LB keys off the lane it
            # routes to); the process goes degraded only when no model
            # has a healthy serving lane left
            models = self.registry.healthz()
            status = "ok" if not models or any(
                m["status"] == "ok" for m in models.values()) else "degraded"
            return {"status": status, "models": models}
        return {"status": "ok"}

    @property
    def address(self):
        """(host, port) actually bound — resolves port=0."""
        return self._httpd.server_address[:2]

    @property
    def url(self):
        host, port = self.address
        return "http://%s:%d" % (host, port)

    def start(self):
        """Serve in a background thread; returns self (chainable)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="model-server")
            self._thread.start()
        return self

    def serve(self):
        """Blocking serve (Ctrl-C to stop)."""
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self, drain=True, timeout=10.0):
        """Graceful shutdown, bounded by ``timeout`` seconds.

        Order matters: first flip :attr:`draining` so new POSTs are shed
        with 503 (instead of racing the socket close), then drain the
        batcher — in-flight requests complete and their HTTP responses go
        out over the still-open listener — and only then stop the
        listener. ``drain=False`` fails queued work immediately with
        ``ServerClosed``."""
        self._stop_started = True
        self._draining = True
        if self.generator is not None:
            # in-flight sequences finish streaming over the still-open
            # listener (same ordering argument as the batcher drain)
            self.generator.close(drain=drain, timeout=timeout)
        if self.batcher is not None:
            self.batcher.close(drain=drain, timeout=timeout)
        if self.registry is not None:
            # every lane drains while the listener is still up, so
            # in-flight responses (streams included) reach their clients
            self.registry.close(drain=drain, timeout=timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self.generator is not None:
            if getattr(self.generator, "metrics", None) is not None:
                self.generator.metrics.unbind_profiler()
            # drop the slot arena's stats registration too — a stopped
            # server must not pin its K/V buffers through the exporter
            gen_engine = getattr(self.generator, "engine", None)
            if gen_engine is not None and hasattr(gen_engine, "close"):
                gen_engine.close()
        if self.engine is not None:
            # stop the background prewarm replay (artifacts_dir= started
            # it) and release the ladder's executables — a stopped server
            # must neither keep compiling rungs nor pin its XLA programs
            self.engine.close()
        self.metrics.unbind_profiler()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
