"""Sharded decode: the PR 15 planner threaded through the decode engine.

:class:`ShardedDecodeEngine` is a
:class:`~mxnet_tpu.serving.generation.decode.DecodeEngine` whose
programs compile against a serving :class:`ShardingPlan
<mxnet_tpu.parallel.planner.ShardingPlan>`:

- the model's parameters are committed onto ``plan.mesh()`` by the
  naming convention (``stack_expert_*`` over ``('pp', 'ep')`` — the MoE
  stack serves expert-parallel through the plain ``moe_ffn`` einsums,
  GSPMD inserting the all_to_alls);
- the :class:`SlotKVCache` arenas are committed onto the SAME mesh
  (:func:`~.placement.arena_spec`), and every commit re-asserts the
  canonical arena sharding so a program output whose sharding GSPMD
  chose differently can never change the next step's program identity
  (which would silently recompile behind the stable cache signature);
- every host-side input is committed replicated
  (:class:`~.placement.MeshCommittedOp`), making the committed-sharding
  part of program identity exact — the fused decode step still compiles
  exactly once, and membership churn still compiles nothing.

AOT: :meth:`export_artifacts` writes ALL program families (decode,
prefill, chunk, prefix insert/extract) into one ``.mxa`` whose
fingerprint covers the mesh axis names and sizes
(``aot.fingerprint(mesh)``), so a multi-chip replica restart
deserializes machine code for its exact mesh — and a single-chip
artifact can never be silently installed into a sharded lane (typed
fallback + ``cachedop.pcache.fallback`` row instead).
"""
from __future__ import annotations

import os

from ... import aot as _aot
from ... import config as _config
from ... import pcache as _pcache
from ...parallel.planner import plan_serving
from ..generation.decode import DecodeEngine
from ..generation.kvcache import SlotKVCache
from .placement import (MeshCommittedOp, arena_sharding, arena_spec,
                        place_params)

__all__ = ["ShardedDecodeEngine", "ShardedSlotKVCache"]

# which positional args of each program family are the K/V arenas (the
# only mesh-sharded inputs; everything else dispatches replicated)
_ARENA_ARGS = {
    "decode": (4, 5),          # tokens, lengths, temps, key, K, V
    "prefill": (3, 4),         # tokens, length, slot, K, V
    "chunk": (3, 4),           # tokens, start, slot, K, V
    "prefix_insert": (3, 4),   # k_slab, v_slab, slot, K, V
    "prefix_extract": (0, 1),  # K, V, slot
}


class ShardedSlotKVCache(SlotKVCache):
    """SlotKVCache whose arenas live committed on a mesh.

    :meth:`bind` places the freshly-zeroed arenas; :meth:`commit`
    re-asserts the canonical sharding on every functional update — a
    device_put that is a no-op when the program output already carries
    it (the common case), and a reshard rather than a recompile when
    GSPMD picked a different output layout."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.arena_sharding = None

    def bind(self, sharding):
        """Commit both arenas onto ``sharding`` (NamedSharding over the
        plan's mesh); subsequent commits keep them there."""
        import jax
        from ...ndarray.ndarray import NDArray
        self.arena_sharding = sharding
        self.k_arena = NDArray(jax.device_put(self.k_arena._data, sharding))
        self.v_arena = NDArray(jax.device_put(self.v_arena._data, sharding))
        return self

    def _reassert(self, arena):
        import jax
        from ...ndarray.ndarray import NDArray
        if getattr(arena._data, "sharding", None) == self.arena_sharding:
            return arena
        return NDArray(jax.device_put(arena._data, self.arena_sharding))

    def commit(self, k_arena, v_arena):
        if self.arena_sharding is not None:
            k_arena = self._reassert(k_arena)
            v_arena = self._reassert(v_arena)
        super().commit(k_arena, v_arena)


class ShardedDecodeEngine(DecodeEngine):
    """Slot-batched decoder compiled against a serving ShardingPlan.

    Parameters beyond :class:`DecodeEngine`'s:

    plan : ShardingPlan, optional
        The placement to serve under. When omitted, one is computed
        with :func:`~mxnet_tpu.parallel.planner.plan_serving` from the
        model's own profile at ``(num_slots, max_seq)`` geometry — the
        latency-weighted serving objective, honoring the
        ``MXNET_SERVE_PLAN_*`` knobs.
    devices / n_devices : optional
        The device pool to mesh over (default: all local devices).
        ``replan`` after a chip-host loss is a rebuild on the surviving
        pool — see :class:`~.replica.ShardedReplica`.
    hbm_bytes / kv_bytes : optional
        Per-device memory budget and KV-arena burden for the plan
        search (``kv_bytes`` defaults to this engine's actual arena
        footprint).
    param_rules : optional
        Extra (regex -> PartitionSpec) placement rules, PREPENDED to
        the plan's naming-convention rules (first match wins).
    """

    def __init__(self, model, plan=None, profile=None, devices=None,
                 n_devices=None, hbm_bytes=None, kv_bytes=None,
                 num_slots=None, max_seq=None, dtype="float32",
                 param_rules=None, name="sharded_generation", **kwargs):
        import jax
        import numpy as _np
        num_slots = int(num_slots or _config.get("MXNET_GEN_SLOTS"))
        max_seq = int(max_seq or min(_config.get("MXNET_GEN_MAX_SEQ"),
                                     model.max_len))
        if devices is None:
            devices = list(jax.devices())
            if n_devices:
                devices = devices[:int(n_devices)]
        if kv_bytes is None:
            kv_bytes = (2 * model.num_layers * num_slots * max_seq *
                        model.num_heads * model.head_dim *
                        _np.dtype(dtype).itemsize)
        if plan is None:
            if profile is None:
                profile = model.profile(num_slots, seq=max_seq)
            plan = plan_serving(len(devices), profile,
                                hbm_bytes=hbm_bytes, kv_bytes=int(kv_bytes))
        self.plan = plan
        self._mesh = plan.mesh(devices)
        rules = list(param_rules or []) + list(plan.param_rules())
        self._param_shardings = place_params(model, self._mesh, rules)
        cache = ShardedSlotKVCache.for_model(model, num_slots, max_seq,
                                             dtype=dtype, name=name)
        cache.bind(arena_sharding(plan, self._mesh,
                                  cache.k_arena.shape))
        super().__init__(model, cache=cache, name=name, **kwargs)
        # re-home every program family on mesh-committed dispatch: the
        # recorded per-signature shardings then cover ALL inputs, and
        # AOT export re-lowers exactly the SPMD programs dispatch ran
        for attr in ("_decode_op", "_prefill_op", "_chunk_op",
                     "_insert_op", "_extract_op"):
            op = getattr(self, attr)
            setattr(self, attr,
                    MeshCommittedOp(op._fn, self._mesh, name=op._name))

    def _sample_first(self, logits_row, temperature):
        # the fused sampler runs EAGERLY on one logits row; a
        # mesh-committed row can't mix with the host-side temps/key
        # (committed to the default device), so gather it first — one
        # (V,) vector, the same bytes asnumpy() would move anyway
        import jax
        from ...ndarray.ndarray import NDArray
        data = logits_row._data
        s = getattr(data, "sharding", None)
        if getattr(getattr(s, "mesh", None), "size", 1) > 1:
            logits_row = NDArray(jax.device_put(data, jax.devices()[0]))
        return super()._sample_first(logits_row, temperature)

    # ---- introspection ----------------------------------------------------
    @property
    def mesh(self):
        return self._mesh

    def mesh_info(self):
        """The mesh identity the fleet/gateway layers carry per replica:
        axis names+sizes (the fingerprint's ``mesh`` entry), chip count,
        and the plan that produced it."""
        p = self.plan
        return {"axes": _aot.mesh_axes(self._mesh),
                "n_devices": int(self._mesh.size),
                "plan": {"dp": p.dp, "pp": p.pp, "ep": p.ep, "sp": p.sp},
                "arena_spec": str(arena_spec(p, self.cache.k_arena.shape))}

    def param_shardings(self):
        """``{param_name: NamedSharding}`` as placed at build."""
        return dict(self._param_shardings)

    def _op_families(self):
        return (("decode", self._decode_op),
                ("prefill", self._prefill_op),
                ("chunk", self._chunk_op),
                ("prefix_insert", self._insert_op),
                ("prefix_extract", self._extract_op))

    def _family_shardings(self, family, sig):
        """Committed input shardings for one artifact record: arenas on
        the canonical arena sharding, everything else replicated — the
        exact placement :class:`MeshCommittedOp` dispatches under."""
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(self._mesh, PartitionSpec())
        arena_pos = _ARENA_ARGS.get(family, ())
        shapes, _train = sig
        return tuple(self.cache.arena_sharding if i in arena_pos else repl
                     for i in range(len(shapes)))

    # ---- AOT: sharded executables in the .mxa container -------------------
    def export_artifacts(self, directory):
        """Serialize every resident program of every family into ONE
        ``executables.mxa`` whose fingerprint covers the mesh
        (``aot.fingerprint(self.mesh)``). The header's ``extra``
        records the family layout (record counts per family, in order)
        and the plan, so :meth:`load_artifacts` can route records back
        and the fleet manifest carries the mesh with the artifact.
        Returns the header dict."""
        records, families = [], []
        for fam, op in self._op_families():
            recs = op.serialize()
            if recs:
                families.append([fam, len(recs)])
                records.extend(recs)
        if not records:
            raise _aot.ArtifactError(
                "no compiled executables to export — serve traffic (or "
                "prefill+decode once) before export_artifacts()")
        os.makedirs(directory, exist_ok=True)
        p = self.plan
        return _aot.write_artifact(
            os.path.join(directory, _aot.ARTIFACT_NAME), records,
            extra={"name": self._name, "engine": "sharded_decode",
                   "families": families,
                   "plan": {"dp": p.dp, "pp": p.pp, "ep": p.ep,
                            "sp": p.sp},
                   "mesh": _aot.mesh_axes(self._mesh)},
            fp=_aot.fingerprint(self._mesh))

    def load_artifacts(self, directory, strict=False):
        """Install a sharded artifact: fingerprint-gated on THIS lane's
        mesh (``current=aot.fingerprint(self.mesh)``), so a single-chip
        artifact — or one exported for any other mesh shape — is
        skipped with a ``cachedop.pcache.fallback`` row and the lane
        compiles normally, never crashes. Loaded signatures are
        re-seeded with their committed input shardings
        (:meth:`CachedOp.record_shardings`) so a later re-export still
        lowers the same SPMD programs. Returns executables installed."""
        path = directory
        if os.path.isdir(directory):
            path = os.path.join(directory, _aot.ARTIFACT_NAME)
        header = _aot.read_artifact_header(path)   # typed on corrupt
        fp = header.get("fingerprint")
        current = _aot.fingerprint(self._mesh)
        where = "ShardedDecodeEngine(%s)" % self._name
        if not _aot.fingerprint_matches(fp, current=current):
            _pcache.note_aot_fallback(
                "fingerprint mismatch: %s"
                % "; ".join(_aot.fingerprint_diff(fp, current=current)),
                where=where)
            return 0
        header, records = _aot.read_artifact(path)
        families = header.get("extra", {}).get("families") or []
        if not families:
            _pcache.note_aot_fallback(
                "artifact has no family layout (not a sharded-decode "
                "export)", where=where)
            return 0
        ops = dict(self._op_families())
        loaded, idx = 0, 0
        for fam, count in families:
            recs = records[idx:idx + int(count)]
            idx += int(count)
            op = ops.get(fam)
            if op is None:
                _pcache.note_aot_fallback(
                    "unknown program family %r in artifact" % (fam,),
                    where=where)
                continue
            for rec in recs:
                op.record_shardings(
                    rec["signature"],
                    self._family_shardings(fam, rec["signature"]))
            try:
                loaded += op.deserialize(recs)
            except _aot.ArtifactError as exc:
                if strict:
                    raise
                _pcache.note_aot_fallback(str(exc), where=where)
        return loaded
