"""Mesh placement for the sharded serving lane.

Three small, composable pieces sit between a
:class:`~mxnet_tpu.parallel.planner.ShardingPlan` and the serving
engines:

- :func:`place_params` — commit a block's parameters onto the plan's
  mesh by the documented naming convention (``stack_expert_*`` →
  ``P('pp', 'ep')``, ``stack_*`` → ``P('pp')``, everything else
  replicated). The committed shardings are what makes ``jax.jit``
  compile ONE SPMD program: the serving engines' CachedOps see sharded
  inputs/closures and XLA's partitioner inserts the all_to_alls the
  placement implies — no shard_map in the decode path.
- :func:`arena_spec` — the PartitionSpec for a
  :class:`~mxnet_tpu.serving.generation.kvcache.SlotKVCache` arena
  ``(layers, slots, seq, heads, head_dim)``: layers over ``pp``, slots
  over the data axes, and only when the sizes divide evenly (a dim that
  doesn't divide is left whole rather than producing a ragged shard).
- :class:`MeshCommittedOp` — a CachedOp that commits every *uncommitted*
  input onto the mesh (replicated) before dispatch. Program identity on
  a mesh includes the committed input shardings (see
  ``cached_op._active_sharding``); committing the small host-side args
  (tokens, lengths, temperatures, keys) makes that identity exact and
  stable, so AOT export re-lowers the very program dispatch runs and a
  restart from the artifact compiles nothing.
"""
from __future__ import annotations

import re

from ...cached_op import CachedOp

__all__ = ["place_params", "arena_spec", "arena_sharding",
           "MeshCommittedOp"]


def place_params(block, mesh, rules):
    """Commit ``block``'s parameters onto ``mesh`` per (regex ->
    PartitionSpec) ``rules`` (first match wins; unmatched params are
    replicated). The placement happens IN the block's parameter storage
    — the engines' traced programs read ``param.data()._data`` and close
    over the committed values — and each value is copied into an owned
    buffer first (the ShardedTrainer idiom: device_put alone can alias
    the source buffer for the shard landing on the source device).
    Returns ``{param_name: NamedSharding}`` for introspection."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    placed = {}
    for p in block.collect_params().values():
        spec = PartitionSpec()
        for pat, s in rules:
            if re.search(pat, p.name):
                spec = s
                break
        s = NamedSharding(mesh, spec)
        nd_handle = p.data()
        v = jnp.array(nd_handle._data, copy=True)
        nd_handle._data = jax.device_put(v, s)
        placed[p.name] = s
    return placed


def arena_spec(plan, arena_shape):
    """PartitionSpec for a KV arena ``(layers, slots, seq, heads,
    head_dim)`` under ``plan``: layers over ``pp``, slots over the data
    axes — each only when the dim divides evenly, else that dim stays
    whole. ``sp`` belongs to the data axes at serving time (one token
    per slot per step: there is no sequence dim to split), so it shards
    slots, keeping every mesh axis in the arena's sharding."""
    from jax.sharding import PartitionSpec

    layers, slots = int(arena_shape[0]), int(arena_shape[1])
    layer_axis = "pp" if plan.pp > 1 and layers % plan.pp == 0 else None
    data = tuple(ax for ax, size in
                 (("dp", plan.dp), ("ep", plan.ep), ("sp", plan.sp))
                 if size > 1)
    n_data = plan.dp * plan.ep * plan.sp
    slot_axes = data if data and slots % n_data == 0 else None
    return PartitionSpec(layer_axis, slot_axes)


def arena_sharding(plan, mesh, arena_shape):
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, arena_spec(plan, arena_shape))


class MeshCommittedOp(CachedOp):
    """CachedOp whose every input is committed onto one mesh.

    Inputs already committed onto the mesh (the arenas, the placed
    params closed over by the traced fn) pass through untouched;
    uncommitted host-side arrays are device_put replicated. The result:
    the per-signature committed-sharding record CachedOp keeps for AOT
    export covers EVERY argument, so the serialized SPMD program and
    the dispatched one are the same program, and a deserialized
    executable never sees an input placement it wasn't compiled for
    (which would demote the AOT hit to a recompile)."""

    def __init__(self, fn, mesh, batch_axes=None, **kwargs):
        """``batch_axes``: optional mesh-axis tuple — inputs whose
        leading dim divides the axes' total size are committed
        batch-sharded over them instead of replicated (the predict-lane
        rule; the decode lane leaves its small per-slot vectors
        replicated and shards only the arenas)."""
        super().__init__(fn, **kwargs)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        self._mesh = mesh
        self._replicated = NamedSharding(mesh, PartitionSpec())
        self._batch = None
        self._batch_n = 1
        if batch_axes:
            axes = tuple(batch_axes)
            self._batch = NamedSharding(mesh, PartitionSpec(axes))
            n = 1
            for ax in axes:
                n *= int(mesh.shape[ax])
            self._batch_n = n
        self._device_put = jax.device_put

    def _commit(self, a):
        from ...ndarray.ndarray import NDArray
        if not isinstance(a, NDArray):
            return a
        s = getattr(a._data, "sharding", None)
        mesh = getattr(s, "mesh", None)
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            return a
        target = self._replicated
        if self._batch is not None and a.shape and \
                a.shape[0] % self._batch_n == 0:
            target = self._batch
        return NDArray(self._device_put(a._data, target))

    def __call__(self, *args, **kwargs):
        import jax
        if any(isinstance(getattr(a, "_data", None), jax.core.Tracer)
               for a in args):
            return super().__call__(*args, **kwargs)
        return super().__call__(*[self._commit(a) for a in args], **kwargs)
