"""ShardedInferenceEngine: the bucketed predict lane on a planned mesh.

Same contract as :class:`~mxnet_tpu.serving.engine.InferenceEngine`
(bucket ladder bounds compiles, pad/unpad batch invariant, warmup /
prewarm / AOT artifacts), with the model's parameters committed onto a
serving :class:`~mxnet_tpu.parallel.planner.ShardingPlan`'s mesh and
batches committed batch-sharded over the plan's data axes — each bucket
rung compiles to ONE SPMD program over all M chips.

AOT artifacts ride the mesh-aware fingerprint
(``aot.fingerprint(mesh)``): a restart of the same mesh shape installs
machine code and compiles nothing; any other topology — including the
single-chip lane — falls back with a ``cachedop.pcache.fallback`` row.
"""
from __future__ import annotations

from ... import aot as _aot
from ...parallel.planner import plan_serving
from ..engine import InferenceEngine
from .placement import MeshCommittedOp, place_params

__all__ = ["ShardedInferenceEngine"]


class ShardedInferenceEngine(InferenceEngine):
    """Bucketed inference engine compiled against a ShardingPlan.

    ``plan`` may be given directly; otherwise ``profile`` (a planner
    :class:`~mxnet_tpu.parallel.planner.ModelProfile`, e.g. from
    ``model.profile(batch, seq)``) is planned with
    :func:`~mxnet_tpu.parallel.planner.plan_serving` over the device
    pool. ``param_rules`` are prepended to the plan's naming-convention
    rules (first match wins)."""

    def __init__(self, model, plan=None, profile=None, devices=None,
                 n_devices=None, hbm_bytes=None, kv_bytes=0,
                 param_rules=None, name="sharded_inference", **kwargs):
        import jax
        if devices is None:
            devices = list(jax.devices())
            if n_devices:
                devices = devices[:int(n_devices)]
        if plan is None:
            if profile is None:
                raise ValueError("ShardedInferenceEngine needs a plan or "
                                 "a ModelProfile to plan from")
            plan = plan_serving(len(devices), profile,
                                hbm_bytes=hbm_bytes, kv_bytes=kv_bytes)
        self.plan = plan
        self._mesh = plan.mesh(devices)
        rules = list(param_rules or []) + list(plan.param_rules())
        self._param_shardings = place_params(model, self._mesh, rules)
        super().__init__(model, name=name, **kwargs)
        if self._op is not None:
            self._op = MeshCommittedOp(self._op._fn, self._mesh,
                                       batch_axes=plan.data_axes,
                                       name=name)

    @property
    def mesh(self):
        return self._mesh

    def mesh_info(self):
        """Mesh identity for the fleet/gateway layers: axis names+sizes,
        chip count, and the plan."""
        p = self.plan
        return {"axes": _aot.mesh_axes(self._mesh),
                "n_devices": int(self._mesh.size),
                "plan": {"dp": p.dp, "pp": p.pp, "ep": p.ep, "sp": p.sp}}

    def param_shardings(self):
        return dict(self._param_shardings)

    # ---- AOT: mesh-fingerprinted artifacts --------------------------------
    def _aot_fingerprint(self):
        return _aot.fingerprint(self._mesh)

    def _artifact_extra(self):
        extra = super()._artifact_extra()
        p = self.plan
        extra["mesh"] = _aot.mesh_axes(self._mesh)
        extra["plan"] = {"dp": p.dp, "pp": p.pp, "ep": p.ep, "sp": p.sp}
        return extra

    def _input_shardings_for(self, sig):
        """The committed shardings dispatch uses for ``sig`` — the
        MeshCommittedOp rule (batch-sharded when the leading dim
        divides, else replicated), applied per recorded input."""
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(self._mesh, PartitionSpec())
        batch = NamedSharding(self._mesh, PartitionSpec(self.plan.data_axes))
        n = 1
        for ax in self.plan.data_axes:
            n *= int(self._mesh.shape[ax])
        shapes, _train = sig
        return tuple(batch if shape and shape[0] % n == 0 else repl
                     for shape, _dtype in shapes)

    def load_artifacts(self, directory, strict=False):
        loaded = super().load_artifacts(directory, strict=strict)
        if loaded and self._op is not None:
            # deserialized machine code carries no jax-level shardings:
            # re-seed each installed signature with the dispatch-rule
            # shardings so a later re-export lowers the same SPMD
            # programs instead of single-device ones
            with self._op._dispatch_lock:
                sigs = list(self._op._cache.keys())
            for sig in sigs:
                self._op.record_shardings(sig,
                                          self._input_shardings_for(sig))
        return loaded
