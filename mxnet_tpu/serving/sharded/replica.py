"""ShardedReplica: a planned mesh of M chips serving as ONE unit.

The fleet/gateway layers treat a replica as an opaque URL; this module
gives that URL a mesh. A :class:`ShardedReplica` owns the serving plan,
the :class:`~.decode.ShardedDecodeEngine`, and the generation scheduler
over it — and can **re-form on a smaller pool** when a chip host is
lost: :meth:`replan` runs the serving planner on the surviving devices,
rebuilds the engine against the new mesh (a new plan may move from,
say, ``ep=8`` to ``ep=4``), and replays the AOT artifact — which
installs machine code when the new mesh matches the artifact's
fingerprint and falls back to compiles (one typed
``cachedop.pcache.fallback`` row) when the mesh shrank. Parameters are
re-placed from the live values; a production restart would re-place
from the checkpoint instead — the placement path is identical.

This is the drain-restart unit the gateway sees: ``mesh_info()`` rides
the server's ``/metrics`` ``mesh`` gauge, the gateway's replica table
carries it as the ``mesh`` label, and the autoscaler weights capacity
by chips, not replica count.
"""
from __future__ import annotations

import threading

from ... import aot as _aot
from ...parallel.planner import PlanError, plan_serving
from .decode import ShardedDecodeEngine

__all__ = ["ShardedReplica"]


class ShardedReplica:
    """Own a sharded decode lane end to end: plan -> mesh -> engine,
    with re-plan on device loss.

    Parameters
    ----------
    model : MoETransformerLM-like
        The incremental-decode model (``prefill``/``step``/
        ``prefill_chunk`` + geometry) whose ``stack_*`` naming the plan
        places.
    devices : optional
        Device pool (default: all local). :meth:`replan` shrinks it.
    hbm_bytes : optional
        Per-device budget for the serving feasibility gate (also read
        from ``MXNET_SERVE_PLAN_HBM_BYTES``).
    artifacts_dir : optional
        Sharded ``.mxa`` directory: loaded at build and after every
        re-plan (fingerprint-gated on the CURRENT mesh).
    engine_kwargs : optional
        Forwarded to :class:`ShardedDecodeEngine` (num_slots, max_seq,
        ladder, chunk, ...).
    """

    def __init__(self, model, devices=None, hbm_bytes=None,
                 artifacts_dir=None, engine_kwargs=None,
                 name="sharded_replica"):
        import jax
        self._model = model
        self._hbm = hbm_bytes
        self._artifacts = artifacts_dir
        self._kw = dict(engine_kwargs or {})
        self._name = name
        self._lock = threading.Lock()
        self.generation = 0
        self.engine = None
        self.aot_loaded = 0
        self._build(list(devices) if devices is not None
                    else list(jax.devices()))

    def _build(self, devices):
        self._devices = devices
        self.engine = ShardedDecodeEngine(
            self._model, devices=devices, hbm_bytes=self._hbm,
            name="%s.g%d" % (self._name, self.generation), **self._kw)
        self.aot_loaded = 0
        if self._artifacts:
            try:
                self.aot_loaded = self.engine.load_artifacts(self._artifacts)
            except _aot.ArtifactError:
                # corrupt artifact: the lane compiles normally; the
                # fallback row was already noted by the loader
                self.aot_loaded = 0

    # ---- identity ---------------------------------------------------------
    @property
    def plan(self):
        return self.engine.plan

    @property
    def n_devices(self):
        return len(self._devices)

    def mesh_info(self):
        info = self.engine.mesh_info()
        info["generation"] = self.generation
        return info

    def compile_stats(self):
        return self.engine.compile_stats()

    # ---- fault tolerance --------------------------------------------------
    def replan(self, devices=None, lost=None):
        """Re-form this replica on a surviving device pool.

        ``devices`` is the explicit surviving pool; ``lost`` removes
        devices from the current one instead. Runs the serving planner
        on the survivors (raising the planner's typed
        :class:`~mxnet_tpu.parallel.planner.PlanError` when the model
        no longer fits — the caller drains the replica instead), closes
        the old engine (freeing its executables and arena), rebuilds on
        the new mesh, and replays the AOT artifact under the new mesh's
        fingerprint. In-flight sequences do NOT survive: the gateway
        drain-restarts the replica as a unit, and requests re-enter
        through the prefix-cache handoff. Returns a report dict."""
        with self._lock:
            if devices is None:
                if lost is None:
                    raise ValueError("replan needs devices= or lost=")
                gone = set(id(d) for d in lost)
                devices = [d for d in self._devices if id(d) not in gone]
            if not devices:
                raise PlanError("no surviving devices to re-plan on")
            old = {"plan": str(self.engine.plan),
                   "n_devices": self.n_devices}
            # feasibility first: keep serving on the old (degraded) mesh
            # rather than tearing down a lane the survivors can't hold
            profile = self._kw.get("profile") or self._model.profile(
                self.engine.cache.num_slots,
                seq=self.engine.cache.max_seq)
            new_plan = plan_serving(len(devices), profile,
                                    hbm_bytes=self._hbm)
            self.engine.close()
            self.generation += 1
            self._build(devices)
            return {"generation": self.generation,
                    "from": old,
                    "to": {"plan": str(self.engine.plan),
                           "n_devices": len(devices)},
                    "planned": str(new_plan),
                    "aot_loaded": self.aot_loaded}

    def export_artifacts(self, directory=None):
        """Export the current mesh's executables (defaults to the
        replica's own artifact directory)."""
        directory = directory or self._artifacts
        if not directory:
            raise ValueError("no artifacts directory configured")
        return self.engine.export_artifacts(directory)

    def close(self):
        self.engine.close()
