"""mxnet_tpu.serving.sharded — the sharded multi-chip inference lane.

PR 15's elastic planner composed dp x pp x ep for *training*; this
package threads the same :class:`~mxnet_tpu.parallel.planner.ShardingPlan`
machinery through the serving stack, under the serving objective
(:func:`~mxnet_tpu.parallel.planner.plan_serving`: decode latency —
serial HBM weight reads + latency-priced collectives — instead of
training comm volume):

- :mod:`placement <.placement>` — commit params / KV arenas / host
  inputs onto the plan's mesh (GSPMD then partitions every program);
- :class:`ShardedDecodeEngine <.decode.ShardedDecodeEngine>` — the
  fused fixed-signature decode step compiled against the plan's
  shardings: MoE stacks serve expert-parallel, the slot arena is
  mesh-sharded, and membership churn still compiles nothing;
- :class:`ShardedInferenceEngine <.engine.ShardedInferenceEngine>` —
  the bucketed predict lane, batch-sharded over the plan's data axes;
- :class:`ShardedReplica <.replica.ShardedReplica>` — "a planned mesh
  of M chips" as one drain-restart unit, surviving chip-host loss by
  re-planning on the surviving pool.

AOT artifacts from this lane fingerprint the MESH (axis names+sizes,
``aot.fingerprint(mesh)``), so a multi-chip replica restarts with zero
XLA compiles and a single-chip artifact can never be silently installed
into a sharded lane.
"""
from .decode import ShardedDecodeEngine, ShardedSlotKVCache
from .engine import ShardedInferenceEngine
from .placement import (MeshCommittedOp, arena_sharding, arena_spec,
                        place_params)
from .replica import ShardedReplica

__all__ = ["ShardedDecodeEngine", "ShardedSlotKVCache",
           "ShardedInferenceEngine", "ShardedReplica", "MeshCommittedOp",
           "place_params", "arena_spec", "arena_sharding"]
