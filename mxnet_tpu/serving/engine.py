"""InferenceEngine: shape-bucketed, compile-bounded model execution.

Role parity: the reference's deployment executors — the C Predict API and
MXNet Model Server both run a loaded symbol through a bound executor whose
shapes are fixed at bind time (`src/c_api/c_predict_api.cc`). On the TPU
stack every *new* input signature is an XLA recompile (seconds, not
microseconds), so serving traffic with arbitrary batch sizes would melt the
compile cache. The classic fix (TF-Serving batching, Clipper) is a bucket
ladder: pad the batch axis up to the nearest configured bucket so the number
of live executables is bounded by ``len(buckets)`` regardless of traffic.

The executor cache itself is the CachedOp LRU (``mxnet_tpu.cached_op``):
the engine wraps the model in one CachedOp, the bucket ladder bounds the
signatures it can see, and ``CachedOp.cache_stats()`` provides the
compile/hit/eviction counters surfaced at ``/metrics``.

Padding invariant: pad rows are zeros appended on axis 0 and sliced back
off every output's axis 0 — the same pad/unpad contract as
``BaseModule.predict`` with ``NDArrayIter(last_batch_handle="pad")``.
Models whose outputs don't carry the batch on axis 0 can't be served
through bucket padding.
"""
from __future__ import annotations

import bisect
import json
import os
import threading

import numpy as _np

from .. import aot as _aot
from .. import config as _config
from .. import pcache as _pcache
from ..cached_op import CachedOp
from ..ndarray import ndarray as _nd
from ..observability import tracer as _trace
from ..resilience import retry as _retry

__all__ = ["InferenceEngine", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def _as_ndarray(x, dtype=None):
    if isinstance(x, _nd.NDArray):
        return x
    return _nd.array(_np.asarray(x), dtype=dtype)


class InferenceEngine:
    """Run a model with batch-axis bucketing and a bounded executor cache.

    Parameters
    ----------
    model : callable
        Anything mapping NDArray inputs to an NDArray (or list/tuple of
        NDArrays): a gluon ``Block``/``HybridBlock``, a ``SymbolBlock``
        loaded from export artifacts (see :meth:`load`), or a plain
        function over NDArrays. All inputs and outputs must carry the
        batch on axis 0.
    buckets : sequence of int
        The batch-size ladder. Incoming batches are padded up to the
        smallest bucket >= n; batches larger than ``max(buckets)`` are
        split into ``max(buckets)``-row chunks. Compiles are bounded by
        ``len(buckets)``.
    jit : bool
        Compile through CachedOp (default). ``jit=False`` calls the model
        eagerly — for python-level models in tests, or models that are
        already internally hybridized.
    metrics : ServingMetrics, optional
        If given, its executor-cache gauge is wired to :meth:`stats`.
    retry_policy : RetryPolicy, optional
        Wrapped around every bucketed execution in :meth:`predict` so
        transient model faults are absorbed per chunk. ``None`` (default)
        uses the env-configured ``retry.engine`` policy; ``False`` disables.
    """

    def __init__(self, model, buckets=DEFAULT_BUCKETS, jit=True,
                 metrics=None, retry_policy=None, name="inference_engine"):
        if retry_policy is None:
            retry_policy = _retry.named_policy("retry.engine")
        self._retry = retry_policy or None
        if not buckets:
            raise ValueError("need at least one bucket size")
        self._buckets = sorted(set(int(b) for b in buckets))
        if self._buckets[0] < 1:
            raise ValueError("bucket sizes must be >= 1")
        self._model = model
        self._name = name
        self._jit = bool(jit)
        self._lock = threading.Lock()
        self._buckets_seen = set()
        # live traffic ledger: per bucket, how often it was hit and the
        # exact padded signature it runs under — the source of the
        # warmup manifest a restart replays in frequency order
        self._traffic = {}
        self._prewarm = {"status": "idle", "completed": 0, "total": 0,
                         "error": None}
        self._prewarm_thread = None
        self._prewarm_stop = False
        if jit:
            def _fn(*args):
                out = model(*args)
                return out
            self._op = CachedOp(_fn, name=name)
        else:
            self._op = None
        self._metrics = metrics
        if metrics is not None:
            metrics.set_cache_stats_fn(self.stats)

    # ---- loading ----------------------------------------------------------
    @staticmethod
    def load(path, input_names=("data",), epoch=0, ctx=None, **kwargs):
        """Build an engine from ``block.export`` artifacts
        (``path-symbol.json`` + ``path-%04d.params``) via
        ``SymbolBlock.imports`` — the deployment entry point."""
        from ..gluon.block import SymbolBlock
        symbol_file = "%s-symbol.json" % path
        params_file = "%s-%04d.params" % (path, epoch)
        import os
        if not os.path.exists(params_file):
            params_file = None
        block = SymbolBlock.imports(symbol_file, list(input_names),
                                    params_file, ctx=ctx)
        return InferenceEngine(block, **kwargs)

    # ---- bucketing --------------------------------------------------------
    @property
    def buckets(self):
        return tuple(self._buckets)

    def bucket_for(self, n):
        """Smallest bucket >= n (or max bucket when n exceeds the ladder —
        callers chunk first)."""
        i = bisect.bisect_left(self._buckets, n)
        return self._buckets[min(i, len(self._buckets) - 1)]

    def _run_bucketed(self, arrays):
        """Pad ``arrays`` (each (n, ...)) up to the bucket, run, unpad."""
        n = arrays[0].shape[0]
        bucket = self.bucket_for(n)
        with self._lock:
            self._buckets_seen.add(bucket)
        with _trace.span("serving.engine.execute", bucket=bucket, rows=n):
            padded = []
            for a in arrays:
                if a.shape[0] != n:
                    raise ValueError(
                        "all inputs must share batch size: got %d vs %d"
                        % (a.shape[0], n))
                if n < bucket:
                    fill = _nd.zeros((bucket - n,) + tuple(a.shape[1:]),
                                     dtype=a.dtype)
                    a = _nd.concat(a, fill, dim=0)
                padded.append(a)
            with self._lock:
                rec = self._traffic.get(bucket)
                if rec is None:
                    self._traffic[bucket] = rec = {
                        "count": 0,
                        "shapes": [tuple(a.shape) for a in padded],
                        "dtypes": [str(a.dtype) for a in padded]}
                rec["count"] += 1
            if self._op is not None:
                out = self._op(*padded)
            else:
                out = self._model(*padded)
            multi = isinstance(out, (list, tuple))
            outs = list(out) if multi else [out]
            if n < bucket:
                outs = [o[0:n] for o in outs]
            return outs, multi

    # ---- execution --------------------------------------------------------
    def predict(self, *inputs):
        """Run a batch: each input is (n, ...) (NDArray or array-like).
        Returns outputs with exactly n rows — pad rows never leak out.
        Batches above ``max(buckets)`` are executed in max-bucket chunks
        and re-concatenated."""
        if not inputs:
            raise ValueError("predict() needs at least one input")
        arrays = [_as_ndarray(x) for x in inputs]
        n = arrays[0].shape[0]
        if n == 0:
            raise ValueError("empty batch")
        run = (self._run_bucketed if self._retry is None
               else lambda a: self._retry.call(self._run_bucketed, a))
        cap = self._buckets[-1]
        if n <= cap:
            outs, multi = run(arrays)
            return (outs if multi else outs[0])
        chunks = []
        multi = False
        for start in range(0, n, cap):
            part = [a[start:min(start + cap, n)] for a in arrays]
            outs, multi = run(part)
            chunks.append(outs)
        merged = [_nd.concat(*[c[i] for c in chunks], dim=0)
                  for i in range(len(chunks[0]))]
        return merged if multi else merged[0]

    def __call__(self, *inputs):
        return self.predict(*inputs)

    # ---- warmup & stats ---------------------------------------------------
    def warmup(self, example, dtype=None, threads=None):
        """Eagerly compile every bucket at load time so first-request
        latency never pays an XLA compile. ``example`` is one input (or a
        tuple of inputs, for multi-input models) whose trailing (non-batch)
        dims and dtypes are representative; its batch size is ignored.

        Rungs compile on a thread pool ``threads`` wide (default
        ``MXNET_WARMUP_THREADS``; <= 1 is serial) — each bucket is a
        distinct CachedOp signature and compiles run outside the
        dispatch lock, so N rungs genuinely compile concurrently and
        cold warmup wall-clock drops to roughly the slowest rung on
        multi-core hosts. With AOT artifacts already loaded
        (:meth:`load_artifacts`) warmup compiles nothing — every rung is
        a cache hit that just touches the device once."""
        examples = example if isinstance(example, (list, tuple)) \
            else (example,)
        arrays = [_as_ndarray(x, dtype=dtype) for x in examples]
        batches = [[_nd.zeros((bucket,) + tuple(a.shape[1:]),
                              dtype=a.dtype) for a in arrays]
                   for bucket in self._buckets]
        self._run_many(batches, threads=threads)
        return self

    def _run_many(self, batches, threads=None):
        """Dispatch ``batches`` (each a list of per-input NDArrays)
        through the bucketed path, on a pool when ``threads`` (default
        ``MXNET_WARMUP_THREADS``) allows. The first failure propagates
        after the remaining dispatches finish."""
        if threads is None:
            threads = _config.get("MXNET_WARMUP_THREADS")
        threads = min(int(threads), len(batches))
        if threads <= 1 or len(batches) <= 1:
            for batch in batches:
                self._run_bucketed(batch)
            return
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=threads,
                                thread_name_prefix=self._name + "-warmup") \
                as pool:
            futures = [pool.submit(self._run_bucketed, b) for b in batches]
        for f in futures:
            f.result()

    # ---- AOT artifacts (compile in CI, ship with the checkpoint) ----------
    def _aot_fingerprint(self):
        """The compatibility fingerprint this engine's artifacts are
        exported under and gated against at load. The sharded lane
        overrides this with ``aot.fingerprint(mesh)`` so a single-chip
        artifact can never be silently installed into a multi-chip
        engine (or vice versa)."""
        return _aot.fingerprint()

    def _artifact_extra(self):
        """Header ``extra`` for :meth:`export_artifacts` (overridable)."""
        return {"name": self._name, "buckets": list(self._buckets)}

    def export_artifacts(self, directory, include_warmup=True):
        """Write this engine's compiled ladder as AOT artifacts into
        ``directory``: ``executables.mxa`` (every resident executable as
        PJRT-serialized machine code, via :meth:`CachedOp.serialize
        <mxnet_tpu.cached_op.CachedOp.serialize>`) plus — when traffic
        or warmup has been observed — the ``warmup.json`` replay
        manifest (:meth:`write_warmup_manifest`). Run :meth:`warmup`
        (or real traffic) first so the ladder is resident; run it in CI
        so serving restarts compile **nothing**. Returns the artifact
        header dict."""
        if self._op is None:
            raise ValueError("jit=False engine has no executables to "
                             "export")
        records = self._op.serialize()
        if not records:
            raise _aot.ArtifactError(
                "no compiled executables to export — call warmup() or "
                "serve traffic before export_artifacts()")
        os.makedirs(directory, exist_ok=True)
        header = _aot.write_artifact(
            os.path.join(directory, _aot.ARTIFACT_NAME), records,
            extra=self._artifact_extra(), fp=self._aot_fingerprint())
        if include_warmup:
            manifest = self.warmup_manifest()
            if manifest["traffic"]:
                self.write_warmup_manifest(
                    os.path.join(directory, _aot.WARMUP_NAME))
        return header

    def load_artifacts(self, directory, strict=False):
        """Install AOT executables exported by :meth:`export_artifacts`
        into this engine's CachedOp — zero XLA compiles for every loaded
        signature. ``directory`` may also be the artifact file itself.

        The load is gated on :func:`mxnet_tpu.aot.fingerprint_matches`:
        an artifact exported on a different jax/jaxlib version, backend
        platform, device kind, or device count is machine code for some
        other process — it is *skipped* with a warn-once
        (``cachedop.pcache.fallback`` row) and the engine compiles
        normally, never crashes. Records whose bucket is not on this
        engine's ladder (ladder drift since export) are skipped the same
        way. A corrupt or truncated artifact raises a typed
        :class:`~mxnet_tpu.aot.ArtifactError` (``strict=False`` demotes
        PJRT-level load failures — structurally valid bytes the backend
        refuses — to the fallback path too). Returns the number of
        executables installed."""
        if self._op is None:
            return 0
        path = directory
        if os.path.isdir(directory):
            path = os.path.join(directory, _aot.ARTIFACT_NAME)
        header = _aot.read_artifact_header(path)   # typed on corrupt
        fp = header.get("fingerprint")
        current = self._aot_fingerprint()
        if not _aot.fingerprint_matches(fp, current=current):
            _pcache.note_aot_fallback(
                "fingerprint mismatch: %s"
                % "; ".join(_aot.fingerprint_diff(fp, current=current)),
                where="InferenceEngine(%s)" % self._name)
            return 0
        header, records = _aot.read_artifact(path)
        ladder = set(self._buckets)
        usable, skipped = [], 0
        for rec in records:
            shapes, _train = rec["signature"]
            bucket = shapes[0][0][0] if shapes and shapes[0][0] else None
            if bucket in ladder:
                usable.append(rec)
            else:
                skipped += 1
        if not usable:
            _pcache.note_aot_fallback(
                "bucket ladder drift: artifact covers %s, engine ladder "
                "is %s" % (header.get("extra", {}).get("buckets"),
                           list(self._buckets)),
                where="InferenceEngine(%s)" % self._name)
            return 0
        try:
            loaded = self._op.deserialize(usable)
        except _aot.ArtifactError as exc:
            if strict:
                raise
            _pcache.note_aot_fallback(str(exc),
                                      where="InferenceEngine(%s)"
                                      % self._name)
            return 0
        if skipped:
            _pcache.note_aot_fallback(
                "%d of %d artifact executables off the current ladder %s"
                % (skipped, len(records), list(self._buckets)),
                where="InferenceEngine(%s)" % self._name)
        return loaded

    # ---- trace-driven prewarm ---------------------------------------------
    def warmup_manifest(self):
        """The live traffic set as a replayable manifest: per bucket, the
        exact padded signature it runs under and how often it was hit,
        hottest first — what :meth:`prewarm` replays on the next restart
        so the rungs real traffic needs most are ready first."""
        with self._lock:
            traffic = {b: dict(rec) for b, rec in self._traffic.items()}
        entries = [{"bucket": int(b),
                    "count": int(rec["count"]),
                    "shapes": [list(s) for s in rec["shapes"]],
                    "dtypes": list(rec["dtypes"])}
                   for b, rec in traffic.items()]
        entries.sort(key=lambda e: (-e["count"], e["bucket"]))
        return {"format": 1, "name": self._name,
                "buckets": list(self._buckets), "traffic": entries}

    def write_warmup_manifest(self, path):
        """Persist :meth:`warmup_manifest` as JSON (atomic tmp+rename —
        the artifact-publish idiom). Returns the manifest dict."""
        manifest = self.warmup_manifest()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
        return manifest

    def prewarm(self, manifest=None, directory=None, background=False,
                threads=None):
        """Replay a warmup manifest: dispatch one zero-filled batch per
        recorded bucket, **in traffic-frequency order**, so the hottest
        rungs are ready first. ``manifest`` is the dict from
        :meth:`warmup_manifest` (or a path to its JSON); ``directory``
        reads ``warmup.json`` from an artifact directory instead.

        ``background=True`` runs the replay on a daemon thread and
        returns immediately — the restart pattern: load AOT artifacts
        (instant), start serving, and let prewarm touch the rungs while
        requests already flow; a request that beats prewarm to a rung
        simply pays that rung's compile (or AOT/pcache hit) itself.
        Progress is visible in :meth:`prewarm_status`.

        ``threads`` (default ``MXNET_WARMUP_THREADS``; <= 1 is serial)
        replays on a pool, same as :meth:`warmup` — submission stays in
        traffic-frequency order, so the hottest rungs still start (and
        near-always finish) first while a cold replay's wall-clock drops
        to roughly the slowest rung. Returns self."""
        if manifest is None and directory is not None:
            manifest = os.path.join(directory, _aot.WARMUP_NAME)
        if isinstance(manifest, str):
            with open(manifest) as f:
                manifest = json.load(f)
        if not isinstance(manifest, dict) or \
                not isinstance(manifest.get("traffic"), list):
            raise ValueError("not a warmup manifest: need a "
                             "{'traffic': [...]} dict (engine."
                             "warmup_manifest() / warmup.json)")
        entries = sorted(manifest["traffic"],
                         key=lambda e: (-int(e.get("count", 0)),
                                        int(e.get("bucket", 0))))
        batches = []
        for e in entries:
            try:
                batches.append([
                    _nd.zeros(tuple(int(d) for d in shape), dtype=dtype)
                    for shape, dtype in zip(e["shapes"], e["dtypes"])])
            except (KeyError, TypeError, ValueError) as exc:
                raise ValueError("malformed warmup manifest entry %r: %s"
                                 % (e, exc)) from exc
        with self._lock:
            if self._prewarm_thread is not None and \
                    self._prewarm_thread.is_alive():
                raise RuntimeError("prewarm already running")
            self._prewarm_stop = False
            self._prewarm = {"status": "running", "completed": 0,
                             "total": len(batches), "error": None}

        n = threads if threads is not None \
            else _config.get("MXNET_WARMUP_THREADS")
        n = min(int(n), len(batches))

        def _one(batch):
            # the stop flag short-circuits queued work on close(); a
            # dispatch already in flight finishes (XLA compiles are not
            # interruptible) but nothing new starts
            if self._prewarm_stop:
                return False
            self._run_bucketed(batch)
            with self._lock:
                self._prewarm["completed"] += 1
            return True

        def _replay():
            try:
                if n <= 1 or len(batches) <= 1:
                    finished = all(_one(b) for b in batches)
                else:
                    from concurrent.futures import ThreadPoolExecutor
                    with ThreadPoolExecutor(
                            max_workers=n,
                            thread_name_prefix=self._name + "-prewarm") \
                            as pool:
                        futures = [pool.submit(_one, b) for b in batches]
                    finished = all(f.result() for f in futures)
                with self._lock:
                    self._prewarm["status"] = "done" if finished \
                        else "stopped"
            except Exception as exc:  # noqa: BLE001 — surfaced in status
                with self._lock:
                    self._prewarm["status"] = "error"
                    self._prewarm["error"] = "%s: %s" \
                        % (type(exc).__name__, exc)
                if not background:
                    raise

        if background:
            t = threading.Thread(target=_replay, daemon=True,
                                 name=self._name + "-prewarm")
            with self._lock:
                self._prewarm_thread = t
            t.start()
        else:
            _replay()
        return self

    def prewarm_status(self):
        """``{"status": "idle|running|done|error", "completed",
        "total", "error"}`` — the background replay's progress."""
        with self._lock:
            return dict(self._prewarm)

    def close(self):
        """Release the executor cache: every compiled bucket program is
        dropped so a retired model version frees its XLA executables
        instead of pinning them for the process lifetime. The engine
        stays callable (programs recompile on demand) — ``close()`` is a
        resource release, not a poison pill, so a drain that races one
        last request cannot turn it into an error. A running background
        prewarm is stopped first so a retiring lane doesn't recompile
        the rungs it is releasing. Idempotent."""
        self._prewarm_stop = True
        t = self._prewarm_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        if self._op is not None:
            self._op.clear()

    def stats(self):
        """Executor-cache counters for /metrics: bucket ladder, buckets
        actually hit, and the CachedOp LRU's hit/miss/evict counts
        (``compiles`` == misses == XLA compiles issued)."""
        with self._lock:
            seen = sorted(self._buckets_seen)
            prewarm = dict(self._prewarm)
        out = {"buckets": list(self._buckets), "buckets_seen": seen,
               "prewarm": prewarm}
        if self._op is not None:
            cs = self._op.cache_stats()
            out.update(cs)
            out["compiles"] = cs["misses"]
        else:
            out.update({"size": len(seen), "capacity": 0, "hits": 0,
                        "misses": len(seen), "evictions": 0,
                        "compiles": len(seen)})
        return out
