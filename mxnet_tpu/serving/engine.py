"""InferenceEngine: shape-bucketed, compile-bounded model execution.

Role parity: the reference's deployment executors — the C Predict API and
MXNet Model Server both run a loaded symbol through a bound executor whose
shapes are fixed at bind time (`src/c_api/c_predict_api.cc`). On the TPU
stack every *new* input signature is an XLA recompile (seconds, not
microseconds), so serving traffic with arbitrary batch sizes would melt the
compile cache. The classic fix (TF-Serving batching, Clipper) is a bucket
ladder: pad the batch axis up to the nearest configured bucket so the number
of live executables is bounded by ``len(buckets)`` regardless of traffic.

The executor cache itself is the CachedOp LRU (``mxnet_tpu.cached_op``):
the engine wraps the model in one CachedOp, the bucket ladder bounds the
signatures it can see, and ``CachedOp.cache_stats()`` provides the
compile/hit/eviction counters surfaced at ``/metrics``.

Padding invariant: pad rows are zeros appended on axis 0 and sliced back
off every output's axis 0 — the same pad/unpad contract as
``BaseModule.predict`` with ``NDArrayIter(last_batch_handle="pad")``.
Models whose outputs don't carry the batch on axis 0 can't be served
through bucket padding.
"""
from __future__ import annotations

import bisect
import threading

import numpy as _np

from ..cached_op import CachedOp
from ..ndarray import ndarray as _nd
from ..observability import tracer as _trace
from ..resilience import retry as _retry

__all__ = ["InferenceEngine", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def _as_ndarray(x, dtype=None):
    if isinstance(x, _nd.NDArray):
        return x
    return _nd.array(_np.asarray(x), dtype=dtype)


class InferenceEngine:
    """Run a model with batch-axis bucketing and a bounded executor cache.

    Parameters
    ----------
    model : callable
        Anything mapping NDArray inputs to an NDArray (or list/tuple of
        NDArrays): a gluon ``Block``/``HybridBlock``, a ``SymbolBlock``
        loaded from export artifacts (see :meth:`load`), or a plain
        function over NDArrays. All inputs and outputs must carry the
        batch on axis 0.
    buckets : sequence of int
        The batch-size ladder. Incoming batches are padded up to the
        smallest bucket >= n; batches larger than ``max(buckets)`` are
        split into ``max(buckets)``-row chunks. Compiles are bounded by
        ``len(buckets)``.
    jit : bool
        Compile through CachedOp (default). ``jit=False`` calls the model
        eagerly — for python-level models in tests, or models that are
        already internally hybridized.
    metrics : ServingMetrics, optional
        If given, its executor-cache gauge is wired to :meth:`stats`.
    retry_policy : RetryPolicy, optional
        Wrapped around every bucketed execution in :meth:`predict` so
        transient model faults are absorbed per chunk. ``None`` (default)
        uses the env-configured ``retry.engine`` policy; ``False`` disables.
    """

    def __init__(self, model, buckets=DEFAULT_BUCKETS, jit=True,
                 metrics=None, retry_policy=None, name="inference_engine"):
        if retry_policy is None:
            retry_policy = _retry.named_policy("retry.engine")
        self._retry = retry_policy or None
        if not buckets:
            raise ValueError("need at least one bucket size")
        self._buckets = sorted(set(int(b) for b in buckets))
        if self._buckets[0] < 1:
            raise ValueError("bucket sizes must be >= 1")
        self._model = model
        self._name = name
        self._jit = bool(jit)
        self._lock = threading.Lock()
        self._buckets_seen = set()
        if jit:
            def _fn(*args):
                out = model(*args)
                return out
            self._op = CachedOp(_fn, name=name)
        else:
            self._op = None
        self._metrics = metrics
        if metrics is not None:
            metrics.set_cache_stats_fn(self.stats)

    # ---- loading ----------------------------------------------------------
    @staticmethod
    def load(path, input_names=("data",), epoch=0, ctx=None, **kwargs):
        """Build an engine from ``block.export`` artifacts
        (``path-symbol.json`` + ``path-%04d.params``) via
        ``SymbolBlock.imports`` — the deployment entry point."""
        from ..gluon.block import SymbolBlock
        symbol_file = "%s-symbol.json" % path
        params_file = "%s-%04d.params" % (path, epoch)
        import os
        if not os.path.exists(params_file):
            params_file = None
        block = SymbolBlock.imports(symbol_file, list(input_names),
                                    params_file, ctx=ctx)
        return InferenceEngine(block, **kwargs)

    # ---- bucketing --------------------------------------------------------
    @property
    def buckets(self):
        return tuple(self._buckets)

    def bucket_for(self, n):
        """Smallest bucket >= n (or max bucket when n exceeds the ladder —
        callers chunk first)."""
        i = bisect.bisect_left(self._buckets, n)
        return self._buckets[min(i, len(self._buckets) - 1)]

    def _run_bucketed(self, arrays):
        """Pad ``arrays`` (each (n, ...)) up to the bucket, run, unpad."""
        n = arrays[0].shape[0]
        bucket = self.bucket_for(n)
        with self._lock:
            self._buckets_seen.add(bucket)
        with _trace.span("serving.engine.execute", bucket=bucket, rows=n):
            padded = []
            for a in arrays:
                if a.shape[0] != n:
                    raise ValueError(
                        "all inputs must share batch size: got %d vs %d"
                        % (a.shape[0], n))
                if n < bucket:
                    fill = _nd.zeros((bucket - n,) + tuple(a.shape[1:]),
                                     dtype=a.dtype)
                    a = _nd.concat(a, fill, dim=0)
                padded.append(a)
            if self._op is not None:
                out = self._op(*padded)
            else:
                out = self._model(*padded)
            multi = isinstance(out, (list, tuple))
            outs = list(out) if multi else [out]
            if n < bucket:
                outs = [o[0:n] for o in outs]
            return outs, multi

    # ---- execution --------------------------------------------------------
    def predict(self, *inputs):
        """Run a batch: each input is (n, ...) (NDArray or array-like).
        Returns outputs with exactly n rows — pad rows never leak out.
        Batches above ``max(buckets)`` are executed in max-bucket chunks
        and re-concatenated."""
        if not inputs:
            raise ValueError("predict() needs at least one input")
        arrays = [_as_ndarray(x) for x in inputs]
        n = arrays[0].shape[0]
        if n == 0:
            raise ValueError("empty batch")
        run = (self._run_bucketed if self._retry is None
               else lambda a: self._retry.call(self._run_bucketed, a))
        cap = self._buckets[-1]
        if n <= cap:
            outs, multi = run(arrays)
            return (outs if multi else outs[0])
        chunks = []
        multi = False
        for start in range(0, n, cap):
            part = [a[start:min(start + cap, n)] for a in arrays]
            outs, multi = run(part)
            chunks.append(outs)
        merged = [_nd.concat(*[c[i] for c in chunks], dim=0)
                  for i in range(len(chunks[0]))]
        return merged if multi else merged[0]

    def __call__(self, *inputs):
        return self.predict(*inputs)

    # ---- warmup & stats ---------------------------------------------------
    def warmup(self, example, dtype=None):
        """Eagerly compile every bucket at load time so first-request
        latency never pays an XLA compile. ``example`` is one input (or a
        tuple of inputs, for multi-input models) whose trailing (non-batch)
        dims and dtypes are representative; its batch size is ignored."""
        examples = example if isinstance(example, (list, tuple)) \
            else (example,)
        arrays = [_as_ndarray(x, dtype=dtype) for x in examples]
        for bucket in self._buckets:
            batch = [_nd.zeros((bucket,) + tuple(a.shape[1:]),
                               dtype=a.dtype) for a in arrays]
            self._run_bucketed(batch)
        return self

    def close(self):
        """Release the executor cache: every compiled bucket program is
        dropped so a retired model version frees its XLA executables
        instead of pinning them for the process lifetime. The engine
        stays callable (programs recompile on demand) — ``close()`` is a
        resource release, not a poison pill, so a drain that races one
        last request cannot turn it into an error. Idempotent."""
        if self._op is not None:
            self._op.clear()

    def stats(self):
        """Executor-cache counters for /metrics: bucket ladder, buckets
        actually hit, and the CachedOp LRU's hit/miss/evict counts
        (``compiles`` == misses == XLA compiles issued)."""
        with self._lock:
            seen = sorted(self._buckets_seen)
        out = {"buckets": list(self._buckets), "buckets_seen": seen}
        if self._op is not None:
            cs = self._op.cache_stats()
            out.update(cs)
            out["compiles"] = cs["misses"]
        else:
            out.update({"size": len(seen), "capacity": 0, "hits": 0,
                        "misses": len(seen), "evictions": 0,
                        "compiles": len(seen)})
        return out
