"""Serving observability: QPS, latency percentiles, batch occupancy.

Role parity: MXNet Model Server's metrics endpoint (``mms/metrics``) — the
reference ecosystem's serving front-end reported requests/sec, latency
percentiles, and worker queue depth per model. Here the counters live
in-process (no sidecar), are exported three ways: programmatically via
:meth:`ServingMetrics.snapshot`, as JSON through the HTTP ``/metrics``
endpoint (``serving.server``), and as rows in the profiler's host-side
aggregate table (``profiler.get_aggregate_stats`` /
``profiler.dumps`` — the analogue of `src/profiler/aggregate_stats.cc`).

Percentiles are computed over a sliding window of recent requests (ring
buffer) so a long-running server reports current behaviour, not lifetime
averages; QPS is likewise measured over the window span.
"""
from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Thread-safe serving counters shared by engine, batcher, and server.

    ``window`` bounds the ring buffer used for latency percentiles and QPS
    (the last N completed requests). Gauges that belong to other components
    (queue depth, executor-cache stats) are pulled through registered
    callbacks at snapshot time so the metrics object never holds locks of
    other subsystems.
    """

    def __init__(self, window=2048, name="serving"):
        self.name = name
        self._lock = threading.Lock()
        self._window = deque(maxlen=window)  # (done_t, latency_s)
        self._c = {"requests": 0, "ok": 0, "errors": 0, "rejected": 0,
                   "expired": 0, "batches": 0, "batched_rows": 0,
                   "worker_errors": 0}
        self._latency_total = 0.0
        self._occupancy_total = 0.0  # sum over batches of rows/capacity
        self._t0 = time.time()
        self._queue_depth_fn = None
        self._cache_stats_fn = None
        self._gauge_fns = {}
        self._bound_provider = None

    # ---- recording (hot path) ---------------------------------------------
    def record_request(self, latency_s, ok=True):
        with self._lock:
            self._c["requests"] += 1
            self._c["ok" if ok else "errors"] += 1
            self._latency_total += latency_s
            self._window.append((time.time(), latency_s))

    def record_rejected(self):
        """Request refused with ServerBusy (bounded-queue backpressure)."""
        with self._lock:
            self._c["rejected"] += 1

    def record_expired(self):
        """Request dropped because its deadline passed while queued."""
        with self._lock:
            self._c["expired"] += 1

    def record_worker_error(self):
        """Batcher worker hit an unexpected exception and closed (the
        robustness contract converted it into ServerClosed for waiters)."""
        with self._lock:
            self._c["worker_errors"] += 1

    def record_batch(self, rows, capacity):
        """One coalesced execution of ``rows`` requests (capacity =
        max_batch_size); occupancy = rows/capacity."""
        with self._lock:
            self._c["batches"] += 1
            self._c["batched_rows"] += rows
            self._occupancy_total += rows / float(max(capacity, 1))

    # ---- gauge hookups ----------------------------------------------------
    def set_queue_depth_fn(self, fn):
        self._queue_depth_fn = fn

    def set_cache_stats_fn(self, fn):
        """``fn()`` -> executor-cache dict (``InferenceEngine.stats``)."""
        self._cache_stats_fn = fn

    def set_gauge_fn(self, name, fn):
        """Attach a named gauge callback (``fn()`` -> JSON-able value),
        pulled at snapshot time — how breaker state and retry counters
        reach the ``/metrics`` endpoint without this module holding
        references into other subsystems' locks."""
        self._gauge_fns[name] = fn

    # ---- reading ----------------------------------------------------------
    def percentiles(self, qs=(50, 95, 99)):
        """Latency percentiles (ms) over the sliding window; nearest-rank."""
        with self._lock:
            lats = sorted(l for _, l in self._window)
        if not lats:
            return {("p%d" % q): 0.0 for q in qs}
        import math
        out = {}
        for q in qs:
            idx = min(len(lats) - 1,
                      max(0, math.ceil(q / 100.0 * len(lats)) - 1))
            out["p%d" % q] = lats[idx] * 1e3
        return out

    def snapshot(self):
        """All counters + derived gauges as one JSON-able dict."""
        with self._lock:
            c = dict(self._c)
            latency_total = self._latency_total
            occupancy_total = self._occupancy_total
            window = list(self._window)
        now = time.time()
        if len(window) >= 2:
            span = max(window[-1][0] - window[0][0], 1e-9)
            qps = (len(window) - 1) / span
        elif c["requests"]:
            qps = c["requests"] / max(now - self._t0, 1e-9)
        else:
            qps = 0.0
        lat = self.percentiles()
        lat["mean"] = (latency_total / c["requests"] * 1e3
                       if c["requests"] else 0.0)
        out = {
            "name": self.name,
            "uptime_s": now - self._t0,
            "qps": qps,
            "latency_ms": lat,
            "batch_occupancy": (occupancy_total / c["batches"]
                                if c["batches"] else 0.0),
            "avg_batch_size": (c["batched_rows"] / c["batches"]
                               if c["batches"] else 0.0),
        }
        out.update(c)
        if self._queue_depth_fn is not None:
            try:
                out["queue_depth"] = self._queue_depth_fn()
            except Exception:
                out["queue_depth"] = None
        if self._cache_stats_fn is not None:
            try:
                out["executor_cache"] = self._cache_stats_fn()
            except Exception:
                out["executor_cache"] = None
        for gname, fn in self._gauge_fns.items():
            try:
                out[gname] = fn()
            except Exception:
                out[gname] = None
        return out

    # ---- profiler integration ---------------------------------------------
    def profiler_rows(self):
        """Rows for the profiler aggregate table:
        ``{name: (calls, total_seconds)}``."""
        with self._lock:
            c = dict(self._c)
            latency_total = self._latency_total
        prefix = self.name
        rows = {
            prefix + ".requests": (c["requests"], latency_total),
            prefix + ".batches": (c["batches"], 0.0),
            prefix + ".rejected": (c["rejected"], 0.0),
            prefix + ".expired": (c["expired"], 0.0),
            prefix + ".worker_errors": (c["worker_errors"], 0.0),
        }
        if self._cache_stats_fn is not None:
            try:
                cs = self._cache_stats_fn() or {}
                for key in ("hits", "misses", "evictions"):
                    if key in cs:
                        rows["%s.cache_%s" % (prefix, key)] = \
                            (int(cs[key]), 0.0)
            except Exception:
                pass
        return rows

    def bind_profiler(self):
        """Register these counters into ``mxnet_tpu.profiler``'s aggregate
        table (idempotent); they then show up in ``profiler.dumps()`` and
        ``profiler.get_aggregate_stats()``."""
        from .. import profiler as _profiler
        if self._bound_provider is None:
            self._bound_provider = self.profiler_rows
            _profiler.register_stats_provider(self._bound_provider)
        return self

    def unbind_profiler(self):
        from .. import profiler as _profiler
        if self._bound_provider is not None:
            _profiler.unregister_stats_provider(self._bound_provider)
            self._bound_provider = None
