"""Serving observability: QPS, latency percentiles, batch occupancy.

Role parity: MXNet Model Server's metrics endpoint (``mms/metrics``) — the
reference ecosystem's serving front-end reported requests/sec, latency
percentiles, and worker queue depth per model. Here the counters live
in-process (no sidecar), are exported three ways: programmatically via
:meth:`ServingMetrics.snapshot`, as JSON through the HTTP ``/metrics``
endpoint (``serving.server``), and as rows in the profiler's host-side
aggregate table (``profiler.get_aggregate_stats`` /
``profiler.dumps`` — the analogue of `src/profiler/aggregate_stats.cc`).

Percentiles are computed over a sliding window of recent requests (ring
buffer) so a long-running server reports current behaviour, not lifetime
averages; QPS is likewise measured over the window span.
"""
from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["ServingMetrics", "GenerationMetrics", "SERVING_PROM_COUNTERS",
           "SERVING_PROM_GAUGES", "GENERATION_PROM_COUNTERS",
           "GENERATION_PROM_GAUGES"]

# Prometheus exposition descriptors (observability/export_prom.py): the
# snapshot() keys that become counter/gauge families, with their HELP
# text — kept NEXT to the counters they describe so adding a counter and
# forgetting its exposition is a one-file diff review, not a hunt.
SERVING_PROM_COUNTERS = (
    ("requests", "completed /predict requests (ok + errors)"),
    ("ok", "requests that returned a model output"),
    ("errors", "requests that failed in the model/batcher"),
    ("rejected", "requests shed with ServerBusy backpressure"),
    ("expired", "requests whose deadline passed while queued"),
    ("batches", "coalesced batch executions"),
    ("batched_rows", "rows executed across all batches"),
    ("worker_errors", "batcher worker deaths (unexpected exceptions)"),
)
SERVING_PROM_GAUGES = (
    ("qps", "completed requests/s over the sliding window"),
    ("batch_occupancy", "mean rows/capacity per batch"),
    ("avg_batch_size", "mean rows per coalesced batch"),
    ("queue_depth", "requests waiting in the batcher queue"),
)
GENERATION_PROM_COUNTERS = (
    ("requests", "retired generation requests (ok + errors)"),
    ("ok", "generation requests retired cleanly"),
    ("errors", "generation requests that failed"),
    ("rejected", "generation requests shed with ServerBusy"),
    ("expired", "generation requests expired in queue"),
    ("prefills", "prompt prefill executions"),
    ("prefill_chunks", "chunked-prefill program calls interleaved with "
     "decode iterations"),
    ("steps", "fused decode iterations"),
    ("step_failures", "decode iterations that faulted"),
    ("tokens_out", "tokens emitted across all sequences"),
    ("retired_eos", "sequences retired on EOS"),
    ("retired_length", "sequences retired on max_new_tokens"),
    ("retired_max_seq", "sequences retired on KV-slot capacity"),
    ("retired_prefill", "sequences retired by a prefill-only lane after "
     "first token + prefix-cache publish (the disaggregation handoff)"),
    ("spec_rounds", "speculative draft-then-verify iterations"),
    ("spec_drafted", "draft tokens proposed to the verify step"),
    ("spec_accepted", "draft tokens the target's greedy choice accepted"),
)
GENERATION_PROM_GAUGES = (
    ("decode_tokens_s", "fleet decode throughput: tokens/s over step time"),
    ("avg_step_occupancy", "mean live slots per fused decode step"),
    ("queue_depth", "generation requests waiting for a slot"),
    ("spec_acceptance_rate", "accepted/drafted over the speculative "
     "decoding lifetime"),
)


def _percentiles(values, qs=(50, 95, 99), scale=1e3):
    """Nearest-rank percentiles over ``values`` (seconds -> ms by
    default); zeros when empty. Shared by the request-latency, TTFT, and
    tokens/s windows so every percentile on /metrics means the same
    thing."""
    vals = sorted(values)
    if not vals:
        return {("p%d" % q): 0.0 for q in qs}
    import math
    out = {}
    for q in qs:
        idx = min(len(vals) - 1, max(0, math.ceil(q / 100.0 * len(vals)) - 1))
        out["p%d" % q] = vals[idx] * scale
    return out


class ServingMetrics:
    """Thread-safe serving counters shared by engine, batcher, and server.

    ``window`` bounds the ring buffer used for latency percentiles and QPS
    (the last N completed requests). Gauges that belong to other components
    (queue depth, executor-cache stats) are pulled through registered
    callbacks at snapshot time so the metrics object never holds locks of
    other subsystems.
    """

    def __init__(self, window=2048, name="serving"):
        self.name = name
        self._lock = threading.Lock()
        self._window = deque(maxlen=window)  # (done_t, latency_s)
        self._c = {"requests": 0, "ok": 0, "errors": 0, "rejected": 0,
                   "expired": 0, "batches": 0, "batched_rows": 0,
                   "worker_errors": 0}
        self._latency_total = 0.0
        self._occupancy_total = 0.0  # sum over batches of rows/capacity
        self._t0 = time.time()
        self._queue_depth_fn = None
        self._cache_stats_fn = None
        self._gauge_fns = {}
        self._bound_provider = None

    # ---- recording (hot path) ---------------------------------------------
    def record_request(self, latency_s, ok=True):
        with self._lock:
            self._c["requests"] += 1
            self._c["ok" if ok else "errors"] += 1
            self._latency_total += latency_s
            self._window.append((time.time(), latency_s))

    def record_rejected(self):
        """Request refused with ServerBusy (bounded-queue backpressure)."""
        with self._lock:
            self._c["rejected"] += 1

    def record_expired(self):
        """Request dropped because its deadline passed while queued."""
        with self._lock:
            self._c["expired"] += 1

    def record_worker_error(self):
        """Batcher worker hit an unexpected exception and closed (the
        robustness contract converted it into ServerClosed for waiters)."""
        with self._lock:
            self._c["worker_errors"] += 1

    def record_batch(self, rows, capacity):
        """One coalesced execution of ``rows`` requests (capacity =
        max_batch_size); occupancy = rows/capacity."""
        with self._lock:
            self._c["batches"] += 1
            self._c["batched_rows"] += rows
            self._occupancy_total += rows / float(max(capacity, 1))

    # ---- gauge hookups ----------------------------------------------------
    def set_queue_depth_fn(self, fn):
        self._queue_depth_fn = fn

    def set_cache_stats_fn(self, fn):
        """``fn()`` -> executor-cache dict (``InferenceEngine.stats``)."""
        self._cache_stats_fn = fn

    def set_gauge_fn(self, name, fn):
        """Attach a named gauge callback (``fn()`` -> JSON-able value),
        pulled at snapshot time — how breaker state and retry counters
        reach the ``/metrics`` endpoint without this module holding
        references into other subsystems' locks."""
        self._gauge_fns[name] = fn

    # ---- reading ----------------------------------------------------------
    def percentiles(self, qs=(50, 95, 99)):
        """Latency percentiles (ms) over the sliding window; nearest-rank."""
        with self._lock:
            lats = [l for _, l in self._window]
        return _percentiles(lats, qs)

    def snapshot(self):
        """All counters + derived gauges as one JSON-able dict."""
        with self._lock:
            c = dict(self._c)
            latency_total = self._latency_total
            occupancy_total = self._occupancy_total
            window = list(self._window)
        now = time.time()
        if len(window) >= 2:
            span = max(window[-1][0] - window[0][0], 1e-9)
            qps = (len(window) - 1) / span
        elif c["requests"]:
            qps = c["requests"] / max(now - self._t0, 1e-9)
        else:
            qps = 0.0
        lat = self.percentiles()
        lat["mean"] = (latency_total / c["requests"] * 1e3
                       if c["requests"] else 0.0)
        out = {
            "name": self.name,
            "uptime_s": now - self._t0,
            "qps": qps,
            "latency_ms": lat,
            "batch_occupancy": (occupancy_total / c["batches"]
                                if c["batches"] else 0.0),
            "avg_batch_size": (c["batched_rows"] / c["batches"]
                               if c["batches"] else 0.0),
        }
        out.update(c)
        if self._queue_depth_fn is not None:
            try:
                out["queue_depth"] = self._queue_depth_fn()
            except Exception:
                out["queue_depth"] = None
        if self._cache_stats_fn is not None:
            try:
                out["executor_cache"] = self._cache_stats_fn()
            except Exception:
                out["executor_cache"] = None
        for gname, fn in self._gauge_fns.items():
            try:
                out[gname] = fn()
            except Exception:
                out[gname] = None
        return out

    # ---- profiler integration ---------------------------------------------
    def profiler_rows(self):
        """Rows for the profiler aggregate table:
        ``{name: (calls, total_seconds)}``."""
        with self._lock:
            c = dict(self._c)
            latency_total = self._latency_total
        prefix = self.name
        rows = {
            prefix + ".requests": (c["requests"], latency_total),
            prefix + ".batches": (c["batches"], 0.0),
            prefix + ".rejected": (c["rejected"], 0.0),
            prefix + ".expired": (c["expired"], 0.0),
            prefix + ".worker_errors": (c["worker_errors"], 0.0),
        }
        if self._queue_depth_fn is not None:
            # live predict-lane backlog (generation lanes already export
            # theirs): the gateway's primary least-loaded routing signal,
            # visible in the aggregate table and scraped off /metrics
            try:
                rows[prefix + ".queue_depth"] = \
                    (int(self._queue_depth_fn()), 0.0)
            except Exception:
                pass
        if self._cache_stats_fn is not None:
            try:
                cs = self._cache_stats_fn() or {}
                for key in ("hits", "misses", "evictions"):
                    if key in cs:
                        rows["%s.cache_%s" % (prefix, key)] = \
                            (int(cs[key]), 0.0)
            except Exception:
                pass
        return rows

    def bind_profiler(self):
        """Register these counters into ``mxnet_tpu.profiler``'s aggregate
        table (idempotent); they then show up in ``profiler.dumps()`` and
        ``profiler.get_aggregate_stats()``."""
        from .. import profiler as _profiler
        if self._bound_provider is None:
            self._bound_provider = self.profiler_rows
            _profiler.register_stats_provider(self._bound_provider)
        return self

    def unbind_profiler(self):
        from .. import profiler as _profiler
        if self._bound_provider is not None:
            _profiler.unregister_stats_provider(self._bound_provider)
            self._bound_provider = None


class GenerationMetrics:
    """Generation-serving counters: time-to-first-token and per-slot
    decode throughput percentiles, plus the admit/step/retire ledger.

    The two latency families that matter for generation and that plain
    request latency can't express:

    - **TTFT** — submit → first streamed token (queue wait + prefill);
      the interactivity number, reported p50/p95/p99 over a sliding
      window.
    - **tokens/s/slot** — each retired request's decode rate
      (``tokens/(done - first_token)``), i.e. per-sequence speed under
      whatever batch occupancy it ran at. The fleet-throughput view
      (``decode_tokens_s``) is total emitted tokens over total step time.

    Exported like :class:`ServingMetrics`: :meth:`snapshot` (the
    ``/metrics`` ``generation`` section when bound by ``ModelServer``)
    and :meth:`bind_profiler` aggregate rows (``generation.*``).
    """

    def __init__(self, window=2048, name="generation"):
        self.name = name
        self._lock = threading.Lock()
        self._ttft = deque(maxlen=window)       # seconds
        self._tps = deque(maxlen=window)        # per-request tokens/s
        self._c = {"requests": 0, "ok": 0, "errors": 0, "rejected": 0,
                   "expired": 0, "prefills": 0, "prefill_chunks": 0,
                   "steps": 0, "step_failures": 0, "tokens_out": 0,
                   "retired_eos": 0, "retired_length": 0,
                   "retired_max_seq": 0, "retired_prefill": 0,
                   "spec_rounds": 0,
                   "spec_drafted": 0, "spec_accepted": 0}
        self._ttft_total = 0.0
        self._step_time = 0.0
        self._prefill_time = 0.0
        self._step_slots = 0
        self._queue_depth_fn = None
        self._engine = None
        self._bound_provider = None

    # ---- recording (scheduler hot path) -----------------------------------
    def record_rejected(self):
        with self._lock:
            self._c["rejected"] += 1

    def record_expired(self):
        with self._lock:
            self._c["expired"] += 1

    def record_ttft(self, seconds):
        with self._lock:
            self._ttft.append(seconds)
            self._ttft_total += seconds

    def record_prefill(self, seconds):
        with self._lock:
            self._c["prefills"] += 1
            self._prefill_time += seconds

    def record_prefill_chunk(self):
        """One chunked-prefill program call (a slice of a long prompt
        interleaved between decode iterations)."""
        with self._lock:
            self._c["prefill_chunks"] += 1

    def record_step(self, live_slots, seconds):
        """One fused decode iteration over ``live_slots`` sequences."""
        with self._lock:
            self._c["steps"] += 1
            self._c["tokens_out"] += live_slots
            self._step_slots += live_slots
            self._step_time += seconds

    def record_spec_round(self, live_slots, drafted, emitted, seconds):
        """One speculative iteration: ``drafted`` proposals went into the
        verify step, ``emitted`` tokens came out across ``live_slots``
        sequences (``emitted - live_slots`` of them were accepted
        drafts; the rest are the per-sequence bonus token)."""
        with self._lock:
            self._c["steps"] += 1
            self._c["spec_rounds"] += 1
            self._c["spec_drafted"] += drafted
            self._c["spec_accepted"] += max(0, emitted - live_slots)
            self._c["tokens_out"] += emitted
            self._step_slots += live_slots
            self._step_time += seconds

    def record_step_failure(self):
        with self._lock:
            self._c["step_failures"] += 1

    def record_done(self, n_tokens, reason, gen_seconds):
        """A sequence retired cleanly after ``n_tokens`` in
        ``gen_seconds`` (first token -> done). The per-slot rate is
        measured over the ``n_tokens - 1`` decode *intervals* inside that
        window — a 1-token sequence spans zero intervals and records no
        rate (its gen_seconds is ~0, and 1/epsilon would poison the
        percentile window)."""
        with self._lock:
            self._c["requests"] += 1
            self._c["ok"] += 1
            key = "retired_%s" % reason
            if key in self._c:
                self._c[key] += 1
            if n_tokens > 1:
                self._tps.append((n_tokens - 1) / max(gen_seconds, 1e-9))

    def record_error(self):
        """A sequence failed (prefill fault, step fault, shutdown)."""
        with self._lock:
            self._c["requests"] += 1
            self._c["errors"] += 1

    # ---- hookups ----------------------------------------------------------
    def set_queue_depth_fn(self, fn):
        self._queue_depth_fn = fn

    def set_engine(self, engine):
        """Wire a ``DecodeEngine`` so snapshots carry its cache occupancy
        and compile counters."""
        self._engine = engine

    # ---- reading ----------------------------------------------------------
    def snapshot(self):
        with self._lock:
            c = dict(self._c)
            ttft = list(self._ttft)
            tps = list(self._tps)
            ttft_total = self._ttft_total
            step_time = self._step_time
            step_slots = self._step_slots
        ttft_ms = _percentiles(ttft)
        ttft_ms["mean"] = (ttft_total / c["prefills"] * 1e3
                           if c["prefills"] else 0.0)
        out = {
            "name": self.name,
            "ttft_ms": ttft_ms,
            # per-request decode rate percentiles (already tokens/s: no
            # ms scaling)
            "tokens_s_per_slot": _percentiles(tps, scale=1.0),
            "decode_tokens_s": (c["tokens_out"] / step_time
                                if step_time > 0 else 0.0),
            "avg_step_occupancy": (step_slots / c["steps"]
                                   if c["steps"] else 0.0),
            "spec_acceptance_rate": (c["spec_accepted"] /
                                     float(c["spec_drafted"])
                                     if c["spec_drafted"] else 0.0),
        }
        out.update(c)
        if self._queue_depth_fn is not None:
            try:
                out["queue_depth"] = self._queue_depth_fn()
            except Exception:
                out["queue_depth"] = None
        if self._engine is not None:
            try:
                out["kvcache"] = self._engine.cache.stats()
                out["compile"] = self._engine.compile_stats()
                if getattr(self._engine, "prefix", None) is not None:
                    out["prefix"] = self._engine.prefix.stats()
            except Exception:
                pass
        return out

    # ---- profiler integration ---------------------------------------------
    def profiler_rows(self):
        with self._lock:
            c = dict(self._c)
            ttft_total = self._ttft_total
            step_time = self._step_time
            prefill_time = self._prefill_time
        prefix = self.name
        rows = {
            prefix + ".requests": (c["requests"], ttft_total),
            prefix + ".tokens": (c["tokens_out"], step_time),
            prefix + ".steps": (c["steps"], step_time),
            prefix + ".prefills": (c["prefills"], prefill_time),
            prefix + ".rejected": (c["rejected"], 0.0),
            prefix + ".expired": (c["expired"], 0.0),
            prefix + ".step_failures": (c["step_failures"], 0.0),
        }
        if self._queue_depth_fn is not None:
            # live backlog gauge: the admission-pressure number operators
            # page on, visible without hitting /metrics
            try:
                rows[prefix + ".queue_depth"] = \
                    (int(self._queue_depth_fn()), 0.0)
            except Exception:
                pass
        return rows

    def bind_profiler(self):
        from .. import profiler as _profiler
        if self._bound_provider is None:
            self._bound_provider = self.profiler_rows
            _profiler.register_stats_provider(self._bound_provider)
        return self

    def unbind_profiler(self):
        from .. import profiler as _profiler
        if self._bound_provider is not None:
            _profiler.unregister_stats_provider(self._bound_provider)
            self._bound_provider = None
