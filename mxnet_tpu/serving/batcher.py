"""DynamicBatcher: adaptive micro-batching with backpressure.

Role parity: MXNet Model Server's BatchAggregator / TF-Serving's
``BasicBatchScheduler`` (Clipper-style adaptive batching). Concurrent
single-sample ``predict`` calls are coalesced by a background worker into
one model execution of up to ``max_batch_size`` rows, flushing early after
``max_latency_ms`` so a lone request is never stuck waiting for peers.
Combined with the engine's bucket ladder this turns serving traffic into a
small, compile-bounded set of XLA programs at high MXU occupancy.

Robustness contract (the part load balancers care about):

- **Bounded queue**: when ``max_queue_size`` requests are waiting, new
  submissions fail fast with :class:`ServerBusy` (HTTP 503) instead of
  growing an unbounded backlog — graceful degradation under overload.
- **Deadlines**: a request that waits past its ``timeout_ms`` is failed
  with :class:`DeadlineExceeded` (HTTP 504) *before* wasting device time.
- **Drain on shutdown**: ``close()`` stops intake, lets the worker finish
  everything already queued, then joins — in-flight requests complete;
  ``close(drain=False)`` fails queued requests with :class:`ServerClosed`.
- **Retry under faults**: transient model failures (injected via the
  ``serving.execute`` chaos point, or real ones listed in the policy's
  ``retryable``) re-run the whole coalesced batch under a
  :class:`~mxnet_tpu.resilience.retry.RetryPolicy` before waiters see an
  error.
- **The worker never dies silently**: an unexpected exception anywhere in
  the worker loop fails the in-flight batch's waiters, drains the queue
  with :class:`ServerClosed`, and marks the batcher closed — blocked
  ``submit()`` callers are never stranded on a dead thread.

Requests carry ONE sample each (no batch axis); results come back as the
matching row of the model output, as numpy (host) arrays — the batcher is
the device→host boundary of the serving path.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as _np

from ..observability import attribution as _attr
from ..observability import tracer as _trace
from ..resilience import chaos as _chaos
from ..resilience import retry as _retry

__all__ = ["DynamicBatcher", "ServingError", "ServerBusy",
           "DeadlineExceeded", "ServerClosed"]


class ServingError(RuntimeError):
    """Base class for typed serving failures."""


class ServerBusy(ServingError):
    """Bounded request queue is full — shed load (HTTP 503)."""


class DeadlineExceeded(ServingError):
    """Request expired in queue before execution (HTTP 504)."""


class ServerClosed(ServingError):
    """Batcher is shut down and no longer accepts work."""


class _Request:
    __slots__ = ("inputs", "future", "enqueue_t", "deadline", "sig",
                 "ctx", "request_id")

    def __init__(self, inputs, timeout_ms, request_id=None):
        self.inputs = inputs
        self.future = Future()
        self.enqueue_t = time.monotonic()
        self.deadline = (self.enqueue_t + timeout_ms / 1e3
                         if timeout_ms else None)
        self.sig = tuple((a.shape, str(a.dtype)) for a in inputs)
        # trace propagation: capture the submitter's span context (the
        # serving.http span) so the worker thread can link this request's
        # queue-wait and execution spans back to it
        self.request_id = request_id
        self.ctx = _trace.current()


class DynamicBatcher:
    """Coalesce concurrent single-sample predictions into batched calls.

    Parameters
    ----------
    fn : callable
        Batched executor: ``fn(*batched_inputs)`` with each input
        ``(rows, ...)``, returning an output (or list/tuple of outputs)
        whose axis 0 is the same ``rows``. An :class:`InferenceEngine`
        fits directly.
    max_batch_size : int
        Max rows coalesced into one execution.
    max_latency_ms : float
        How long the worker holds an open batch waiting for more requests
        (measured from the oldest request's arrival).
    max_queue_size : int
        Bound on waiting requests; beyond it, submissions raise
        :class:`ServerBusy`.
    default_timeout_ms : float, optional
        Per-request deadline applied when ``submit`` doesn't pass one;
        ``None`` = no deadline.
    metrics : ServingMetrics, optional
        Records request latency, batch occupancy, rejections, expiries,
        and exposes live queue depth.
    retry_policy : RetryPolicy, optional
        Applied around each batch execution. ``None`` (default) builds one
        from the ``MXNET_RETRY_*`` env knobs retrying
        :class:`~mxnet_tpu.resilience.chaos.TransientFault`; pass ``False``
        to disable retries entirely.
    """

    def __init__(self, fn, max_batch_size=32, max_latency_ms=5.0,
                 max_queue_size=128, default_timeout_ms=None, metrics=None,
                 retry_policy=None, name="dynamic_batcher"):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if max_queue_size < 1:
            raise ValueError("max_queue_size must be >= 1")
        self._fn = fn
        if retry_policy is None:
            retry_policy = _retry.named_policy("retry.batcher")
        self._retry = retry_policy or None
        self._max_batch = int(max_batch_size)
        self._max_latency_s = max_latency_ms / 1e3
        self._max_queue = int(max_queue_size)
        self._default_timeout_ms = default_timeout_ms
        self._metrics = metrics
        self._queue = deque()
        self._inflight = ()  # batch the worker is executing right now
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closing = False
        self._drain = True
        if metrics is not None:
            metrics.set_queue_depth_fn(lambda: self.queue_depth)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=name + "-worker")
        self._worker.start()

    # ---- client side ------------------------------------------------------
    @property
    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    def submit(self, *inputs, timeout_ms=None, request_id=None):
        """Enqueue one sample (each input WITHOUT batch axis); returns a
        ``concurrent.futures.Future`` resolving to the sample's output row
        (numpy), or a tuple of rows for multi-output models. Raises
        :class:`ServerBusy` / :class:`ServerClosed` synchronously.
        ``request_id`` labels the request's spans in the trace."""
        if timeout_ms is None:
            timeout_ms = self._default_timeout_ms
        arrays = tuple(_np.asarray(x) for x in inputs)
        req = _Request(arrays, timeout_ms, request_id=request_id)
        with self._lock:
            if self._closing:
                raise ServerClosed("batcher is shut down")
            if len(self._queue) >= self._max_queue:
                if self._metrics is not None:
                    self._metrics.record_rejected()
                raise ServerBusy(
                    "request queue full (%d waiting)" % len(self._queue))
            self._queue.append(req)
            self._not_empty.notify()
        return req.future

    def predict(self, *inputs, timeout_ms=None, request_id=None):
        """Blocking single-sample prediction through the shared batch."""
        return self.submit(*inputs, timeout_ms=timeout_ms,
                           request_id=request_id).result()

    def close(self, drain=True, timeout=None):
        """Stop intake; with ``drain`` the worker finishes the backlog
        before exiting, otherwise queued requests fail with
        :class:`ServerClosed`. ``timeout`` bounds the drain: when it
        expires with work still queued, the stragglers are failed with
        :class:`ServerClosed` rather than left blocked forever. Returns
        True when the worker exited cleanly. Idempotent."""
        with self._lock:
            self._closing = True
            self._drain = drain
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._resolve(req.future, exc=ServerClosed(
                        "batcher shut down before execution"))
            self._not_empty.notify_all()
        self._worker.join(timeout)
        if self._worker.is_alive():
            # bounded drain expired: never strand waiters — fail what is
            # still queued AND the batch wedged inside the model call (the
            # worker's own resolve later is a tolerated no-op)
            with self._lock:
                stranded = list(self._queue) + list(self._inflight)
                self._queue.clear()
            for req in stranded:
                self._resolve(req.future, exc=ServerClosed(
                    "drain timed out after %.1fs with request unfinished"
                    % (timeout,)))
            return False
        return True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- worker side ------------------------------------------------------
    def _take_batch(self):
        """Block until work exists, hold the batch window open, then pop up
        to ``max_batch`` signature-compatible requests. Returns (requests,
        expired) or (None, expired) at shutdown."""
        expired = []
        with self._not_empty:
            while True:
                self._drop_expired_locked(expired)
                if expired and not self._queue:
                    # resolve expiries promptly: hand them to _run now
                    # instead of holding them until new work arrives
                    return [], expired
                if self._queue:
                    break
                if self._closing:
                    return None, expired
                self._not_empty.wait(0.05)
            head_t = self._queue[0].enqueue_t
            flush_at = head_t + self._max_latency_s
            # hold the window open for stragglers (closing flushes now)
            while not self._closing and len(self._queue) < self._max_batch:
                rem = flush_at - time.monotonic()
                if rem <= 0:
                    break
                # cap the wait so queued deadlines are enforced promptly
                # even while the batch window is held open
                self._not_empty.wait(min(rem, 0.05))
                self._drop_expired_locked(expired)
                if not self._queue:
                    # everything expired while waiting; start over
                    return [], expired
            # pop the head run of signature-compatible requests; mixed
            # trailing shapes stay queued for the next cycle
            sig = self._queue[0].sig
            batch = []
            leftover = deque()
            while self._queue and len(batch) < self._max_batch:
                req = self._queue.popleft()
                (batch if req.sig == sig else leftover).append(req)
            leftover.extend(self._queue)
            self._queue.clear()
            self._queue.extend(leftover)
            # recorded under the SAME lock that popped the batch: close()
            # must always see these requests in _queue or _inflight, never
            # in neither (the never-strand-waiters contract)
            self._inflight = tuple(batch)
            return batch, expired

    def _drop_expired_locked(self, expired):
        now = time.monotonic()
        kept = deque()
        while self._queue:
            req = self._queue.popleft()
            if req.deadline is not None and now > req.deadline:
                expired.append(req)
            else:
                kept.append(req)
        self._queue.extend(kept)

    @staticmethod
    def _resolve(future, result=None, exc=None):
        """Set a future's outcome, tolerating callers that already
        cancelled it — a cancelled waiter must never kill the worker."""
        try:
            if exc is not None:
                future.set_exception(exc)
            else:
                future.set_result(result)
        except Exception:  # InvalidStateError: waiter cancelled — fine
            pass

    def _run(self):
        # Robustness contract: this thread is the only executor for every
        # blocked submit() caller, so NO exception may terminate it without
        # first resolving all reachable futures and closing intake.
        try:
            while True:
                batch, expired = self._take_batch()
                for req in expired:
                    if self._metrics is not None:
                        try:
                            self._metrics.record_expired()
                        except Exception:
                            pass
                    self._resolve(req.future, exc=DeadlineExceeded(
                        "request expired after queueing %.1f ms"
                        % ((time.monotonic() - req.enqueue_t) * 1e3)))
                if batch is None:
                    return  # closed and (if draining) queue empty
                if not batch:
                    continue
                if _trace.enabled():
                    # the wait each request just finished, recorded after
                    # the fact and linked to its serving.http span — the
                    # "queue" phase of a p99 decomposition
                    popped_t = time.monotonic()
                    for req in batch:
                        _trace.complete("serving.queue_wait",
                                        req.enqueue_t, popped_t,
                                        parent=req.ctx,
                                        request_id=req.request_id)
                if _attr.flight_enabled():
                    # the flight recorder sees queue waits even with
                    # tracing off: one batch-level record (max wait),
                    # not one per member — the ring is for timelines,
                    # not per-request accounting
                    popped_t = time.monotonic()
                    _attr.flight_note(
                        "queue_wait", rows=len(batch),
                        max_wait_ms=(popped_t - min(
                            r.enqueue_t for r in batch)) * 1e3)
                try:
                    self._execute(batch)
                except BaseException as exc:  # _execute's guards failed too
                    for req in batch:
                        self._resolve(req.future, exc=exc)
                    raise
                finally:
                    with self._lock:
                        self._inflight = ()
        except BaseException as exc:  # worker would die: close, don't strand
            self._abort(exc)

    def _abort(self, exc):
        """Unexpected worker failure: transition to closed so future
        submitters fail fast, and fail everything still queued — no
        submit() caller is ever left blocked on a dead worker."""
        with self._lock:
            self._closing = True
            stranded = list(self._queue) + list(self._inflight)
            self._queue.clear()
            self._inflight = ()
        if self._metrics is not None:
            try:
                self._metrics.record_worker_error()
            except Exception:
                pass
        err = ServerClosed("batcher worker died: %s: %s"
                           % (type(exc).__name__, exc))
        err.__cause__ = exc
        for req in stranded:
            self._resolve(req.future, exc=err)

    def _execute(self, batch):
        if not _trace.enabled():
            return self._execute_inner(batch)
        # one execution span for the coalesced batch; a span cannot have
        # many parents, so it adopts the first request's trace and carries
        # every member's request id as an attribute (the summary tool and
        # Perfetto queries join on those)
        with _trace.span("serving.batch_execute", rows=len(batch),
                         request_ids=[r.request_id for r in batch
                                      if r.request_id is not None],
                         parent=batch[0].ctx):
            return self._execute_inner(batch)

    def _execute_inner(self, batch):
        try:
            n_inputs = len(batch[0].inputs)
            with _trace.span("serving.batch_assemble", rows=len(batch)):
                stacked = [_np.stack([r.inputs[i] for r in batch], axis=0)
                           for i in range(n_inputs)]

            def run_model():
                # chaos point INSIDE the retried callable: each retry
                # attempt re-rolls the injection (first-K/every-Nth count
                # attempts), so armed transient faults are absorbed here
                _chaos.point("serving.execute")
                out = self._fn(*stacked)
                multi = isinstance(out, (list, tuple))
                outs = [_np.asarray(o.asnumpy()
                                    if hasattr(o, "asnumpy") else o)
                        for o in (out if multi else [out])]
                return outs, multi

            if self._retry is not None:
                outs, multi = self._retry.call(run_model)
            else:
                outs, multi = run_model()
            for o in outs:
                if o.shape[0] != len(batch):
                    raise ValueError(
                        "model output axis 0 (%d) != batch rows (%d); "
                        "outputs must carry the batch on axis 0"
                        % (o.shape[0], len(batch)))
        except Exception as exc:  # noqa: BLE001 — fail the whole batch
            for req in batch:
                if self._metrics is not None:
                    try:
                        self._metrics.record_request(
                            time.monotonic() - req.enqueue_t, ok=False)
                    except Exception:
                        pass
                self._resolve(req.future, exc=exc)
            return
        # past this point waiters MUST be resolved: a metrics failure may
        # not strand them (satellite: worker-thread death fix)
        try:
            if self._metrics is not None:
                self._metrics.record_batch(len(batch), self._max_batch)
            done_t = time.monotonic()
            for i, req in enumerate(batch):
                row = tuple(o[i] for o in outs) if multi else outs[0][i]
                if self._metrics is not None:
                    self._metrics.record_request(done_t - req.enqueue_t,
                                                 ok=True)
                self._resolve(req.future, result=row)
        except Exception as exc:
            for req in batch:
                if not req.future.done():
                    self._resolve(req.future, exc=exc)
