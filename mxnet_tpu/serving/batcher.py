"""DynamicBatcher: adaptive micro-batching with backpressure.

Role parity: MXNet Model Server's BatchAggregator / TF-Serving's
``BasicBatchScheduler`` (Clipper-style adaptive batching). Concurrent
single-sample ``predict`` calls are coalesced by a background worker into
one model execution of up to ``max_batch_size`` rows, flushing early after
``max_latency_ms`` so a lone request is never stuck waiting for peers.
Combined with the engine's bucket ladder this turns serving traffic into a
small, compile-bounded set of XLA programs at high MXU occupancy.

Robustness contract (the part load balancers care about):

- **Bounded queue**: when ``max_queue_size`` requests are waiting, new
  submissions fail fast with :class:`ServerBusy` (HTTP 503) instead of
  growing an unbounded backlog — graceful degradation under overload.
- **Deadlines**: a request that waits past its ``timeout_ms`` is failed
  with :class:`DeadlineExceeded` (HTTP 504) *before* wasting device time.
- **Drain on shutdown**: ``close()`` stops intake, lets the worker finish
  everything already queued, then joins — in-flight requests complete;
  ``close(drain=False)`` fails queued requests with :class:`ServerClosed`.

Requests carry ONE sample each (no batch axis); results come back as the
matching row of the model output, as numpy (host) arrays — the batcher is
the device→host boundary of the serving path.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as _np

__all__ = ["DynamicBatcher", "ServingError", "ServerBusy",
           "DeadlineExceeded", "ServerClosed"]


class ServingError(RuntimeError):
    """Base class for typed serving failures."""


class ServerBusy(ServingError):
    """Bounded request queue is full — shed load (HTTP 503)."""


class DeadlineExceeded(ServingError):
    """Request expired in queue before execution (HTTP 504)."""


class ServerClosed(ServingError):
    """Batcher is shut down and no longer accepts work."""


class _Request:
    __slots__ = ("inputs", "future", "enqueue_t", "deadline", "sig")

    def __init__(self, inputs, timeout_ms):
        self.inputs = inputs
        self.future = Future()
        self.enqueue_t = time.monotonic()
        self.deadline = (self.enqueue_t + timeout_ms / 1e3
                         if timeout_ms else None)
        self.sig = tuple((a.shape, str(a.dtype)) for a in inputs)


class DynamicBatcher:
    """Coalesce concurrent single-sample predictions into batched calls.

    Parameters
    ----------
    fn : callable
        Batched executor: ``fn(*batched_inputs)`` with each input
        ``(rows, ...)``, returning an output (or list/tuple of outputs)
        whose axis 0 is the same ``rows``. An :class:`InferenceEngine`
        fits directly.
    max_batch_size : int
        Max rows coalesced into one execution.
    max_latency_ms : float
        How long the worker holds an open batch waiting for more requests
        (measured from the oldest request's arrival).
    max_queue_size : int
        Bound on waiting requests; beyond it, submissions raise
        :class:`ServerBusy`.
    default_timeout_ms : float, optional
        Per-request deadline applied when ``submit`` doesn't pass one;
        ``None`` = no deadline.
    metrics : ServingMetrics, optional
        Records request latency, batch occupancy, rejections, expiries,
        and exposes live queue depth.
    """

    def __init__(self, fn, max_batch_size=32, max_latency_ms=5.0,
                 max_queue_size=128, default_timeout_ms=None, metrics=None,
                 name="dynamic_batcher"):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if max_queue_size < 1:
            raise ValueError("max_queue_size must be >= 1")
        self._fn = fn
        self._max_batch = int(max_batch_size)
        self._max_latency_s = max_latency_ms / 1e3
        self._max_queue = int(max_queue_size)
        self._default_timeout_ms = default_timeout_ms
        self._metrics = metrics
        self._queue = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closing = False
        self._drain = True
        if metrics is not None:
            metrics.set_queue_depth_fn(lambda: self.queue_depth)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=name + "-worker")
        self._worker.start()

    # ---- client side ------------------------------------------------------
    @property
    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    def submit(self, *inputs, timeout_ms=None):
        """Enqueue one sample (each input WITHOUT batch axis); returns a
        ``concurrent.futures.Future`` resolving to the sample's output row
        (numpy), or a tuple of rows for multi-output models. Raises
        :class:`ServerBusy` / :class:`ServerClosed` synchronously."""
        if timeout_ms is None:
            timeout_ms = self._default_timeout_ms
        arrays = tuple(_np.asarray(x) for x in inputs)
        req = _Request(arrays, timeout_ms)
        with self._lock:
            if self._closing:
                raise ServerClosed("batcher is shut down")
            if len(self._queue) >= self._max_queue:
                if self._metrics is not None:
                    self._metrics.record_rejected()
                raise ServerBusy(
                    "request queue full (%d waiting)" % len(self._queue))
            self._queue.append(req)
            self._not_empty.notify()
        return req.future

    def predict(self, *inputs, timeout_ms=None):
        """Blocking single-sample prediction through the shared batch."""
        return self.submit(*inputs, timeout_ms=timeout_ms).result()

    def close(self, drain=True, timeout=None):
        """Stop intake; with ``drain`` the worker finishes the backlog
        before exiting, otherwise queued requests fail with
        :class:`ServerClosed`. Idempotent."""
        with self._lock:
            self._closing = True
            self._drain = drain
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    req.future.set_exception(
                        ServerClosed("batcher shut down before execution"))
            self._not_empty.notify_all()
        self._worker.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- worker side ------------------------------------------------------
    def _take_batch(self):
        """Block until work exists, hold the batch window open, then pop up
        to ``max_batch`` signature-compatible requests. Returns (requests,
        expired) or (None, expired) at shutdown."""
        expired = []
        with self._not_empty:
            while True:
                self._drop_expired_locked(expired)
                if expired and not self._queue:
                    # resolve expiries promptly: hand them to _run now
                    # instead of holding them until new work arrives
                    return [], expired
                if self._queue:
                    break
                if self._closing:
                    return None, expired
                self._not_empty.wait(0.05)
            head_t = self._queue[0].enqueue_t
            flush_at = head_t + self._max_latency_s
            # hold the window open for stragglers (closing flushes now)
            while not self._closing and len(self._queue) < self._max_batch:
                rem = flush_at - time.monotonic()
                if rem <= 0:
                    break
                # cap the wait so queued deadlines are enforced promptly
                # even while the batch window is held open
                self._not_empty.wait(min(rem, 0.05))
                self._drop_expired_locked(expired)
                if not self._queue:
                    # everything expired while waiting; start over
                    return [], expired
            # pop the head run of signature-compatible requests; mixed
            # trailing shapes stay queued for the next cycle
            sig = self._queue[0].sig
            batch = []
            leftover = deque()
            while self._queue and len(batch) < self._max_batch:
                req = self._queue.popleft()
                (batch if req.sig == sig else leftover).append(req)
            leftover.extend(self._queue)
            self._queue.clear()
            self._queue.extend(leftover)
            return batch, expired

    def _drop_expired_locked(self, expired):
        now = time.monotonic()
        kept = deque()
        while self._queue:
            req = self._queue.popleft()
            if req.deadline is not None and now > req.deadline:
                expired.append(req)
            else:
                kept.append(req)
        self._queue.extend(kept)

    def _run(self):
        while True:
            batch, expired = self._take_batch()
            for req in expired:
                if self._metrics is not None:
                    self._metrics.record_expired()
                req.future.set_exception(DeadlineExceeded(
                    "request expired after queueing %.1f ms"
                    % ((time.monotonic() - req.enqueue_t) * 1e3)))
            if batch is None:
                return  # closed and (if draining) queue empty
            if not batch:
                continue
            self._execute(batch)

    def _execute(self, batch):
        try:
            n_inputs = len(batch[0].inputs)
            stacked = [_np.stack([r.inputs[i] for r in batch], axis=0)
                       for i in range(n_inputs)]
            out = self._fn(*stacked)
            multi = isinstance(out, (list, tuple))
            outs = [_np.asarray(o.asnumpy() if hasattr(o, "asnumpy") else o)
                    for o in (out if multi else [out])]
            for o in outs:
                if o.shape[0] != len(batch):
                    raise ValueError(
                        "model output axis 0 (%d) != batch rows (%d); "
                        "outputs must carry the batch on axis 0"
                        % (o.shape[0], len(batch)))
        except Exception as exc:  # noqa: BLE001 — fail the whole batch
            for req in batch:
                if self._metrics is not None:
                    self._metrics.record_request(
                        time.monotonic() - req.enqueue_t, ok=False)
                req.future.set_exception(exc)
            return
        if self._metrics is not None:
            self._metrics.record_batch(len(batch), self._max_batch)
        done_t = time.monotonic()
        for i, req in enumerate(batch):
            row = tuple(o[i] for o in outs) if multi else outs[0][i]
            if self._metrics is not None:
                self._metrics.record_request(done_t - req.enqueue_t, ok=True)
            req.future.set_result(row)
