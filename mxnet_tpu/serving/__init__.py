"""mxnet_tpu.serving — dynamic-batching inference subsystem.

The TPU-native answer to MXNet Model Server / the C Predict API
(`src/c_api/c_predict_api.cc`): a request path for exported models where

- :class:`InferenceEngine` (``engine.py``) bounds XLA compiles with a
  shape-bucketed executor cache (pad to a bucket ladder, CachedOp LRU);
- :class:`DynamicBatcher` (``batcher.py``) coalesces concurrent requests
  into batched executions with deadlines and :class:`ServerBusy`
  backpressure;
- :class:`ServingMetrics` (``metrics.py``) exports QPS / latency
  percentiles / occupancy / cache counters, programmatically and through
  the profiler aggregate table;
- :class:`ModelServer` (``server.py``) exposes the whole path over stdlib
  HTTP (``/predict``, ``/healthz``, ``/metrics``).

Quickstart::

    import mxnet_tpu as mx
    net(sample)                      # shape the block, then
    net.export("/tmp/model")         # -> model-symbol.json + params
    eng = mx.serving.InferenceEngine.load("/tmp/model")
    srv = mx.serving.ModelServer(eng, port=8080).start()
    # curl -X POST :8080/predict -d '{"data": [...]}'
"""
from .batcher import (DeadlineExceeded, DynamicBatcher, ServerBusy,
                      ServerClosed, ServingError)
from .engine import DEFAULT_BUCKETS, InferenceEngine
from .fleet import (CanaryController, ChecksumMismatch,
                    CompileBudgetExceeded, FleetError, ManifestError,
                    ModelNotFound, ModelRegistry, ModelVersion,
                    VersionNotFound, verify_manifest, write_manifest)
from .gateway import (Autoscaler, Gateway, GatewayMetrics,
                      NoRoutableReplica, Replica, ReplicaUnavailable)
from .metrics import GenerationMetrics, ServingMetrics
from .server import ModelServer
from . import fleet
from . import gateway
from . import generation
from . import sharded

__all__ = ["InferenceEngine", "DynamicBatcher", "ModelServer",
           "ServingMetrics", "GenerationMetrics", "ServingError",
           "ServerBusy", "DeadlineExceeded", "ServerClosed",
           "DEFAULT_BUCKETS", "generation", "fleet", "ModelRegistry",
           "ModelVersion", "CanaryController", "FleetError",
           "ModelNotFound", "VersionNotFound", "ManifestError",
           "ChecksumMismatch", "CompileBudgetExceeded",
           "write_manifest", "verify_manifest", "gateway", "Gateway",
           "Autoscaler", "GatewayMetrics", "Replica",
           "ReplicaUnavailable", "NoRoutableReplica", "sharded"]
